"""Differential suite for the compressed ragged units wire
(``--wireCodec dict`` — features/wirecodec.py host codec,
ops/ragged.units_from_codes in-jit decode, the codec-aware packed layouts
in features/batch.py).

The parity law: decoded units must be BYTE-identical to the uncompressed
wire on every path — flat pack, shard segments, the coalesced group wire,
the mesh-sharded program — and a model fed the codec wire must produce
bitwise-identical trajectories to one fed the raw wire. The codec changes
wire representation only, never semantics. Fallbacks (uint16 non-ASCII
units, incompressible batches) must ship the raw layout, not fail.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from twtml_tpu.features import wirecodec as wc
from twtml_tpu.features.batch import (
    RaggedUnitBatch,
    align_ragged_shards,
    pack_batch,
    pack_ragged_group,
    pack_ragged_sharded,
    stack_batches,
    unpack_batch,
    wire_composition,
)
from twtml_tpu.features.featurizer import Featurizer
from twtml_tpu.models import StreamingLinearRegressionWithSGD
from twtml_tpu.streaming.sources import SyntheticSource

NOW = 1785320000000


def synthetic(n=128, seed=7):
    return list(SyntheticSource(total=n, seed=seed, base_ms=NOW).produce())


def ragged_batch(statuses, rows=64, unit_bucket=0):
    feat = Featurizer(now_ms=NOW)
    return feat.featurize_batch_ragged(
        statuses, row_bucket=rows, unit_bucket=unit_bucket, pre_filtered=True
    )


def assert_ragged_equal(a: RaggedUnitBatch, b: RaggedUnitBatch):
    for f in ("units", "offsets", "numeric", "label", "mask"):
        x, y = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert x.dtype == y.dtype, f
        np.testing.assert_array_equal(x, y, err_msg=f)
    assert (a.row_len, a.num_shards) == (b.row_len, b.num_shards)


# ---------------------------------------------------------------------------
# codec core: encoder ground truth, C parity, decode twins


def fuzz_buffers(rounds=40, seed=0):
    rng = np.random.default_rng(seed)
    out = [
        np.zeros((0,), np.uint8),
        np.zeros((1,), np.uint8),
        np.zeros((4096,), np.uint8),
        np.frombuffer(
            b"the quick brown fox jumps over https://t.co/Ab12 again and "
            b"again because english text is what the dictionary is for ",
            np.uint8,
        ),
    ]
    for _ in range(rounds):
        n = int(rng.integers(0, 2048))
        out.append(rng.integers(0, 128, n).astype(np.uint8))
        # runs of dictionary-hit pairs at adversarial alignments
        out.append(
            np.frombuffer((b"e " * int(rng.integers(1, 64)))[1:], np.uint8)
        )
    return out


def test_host_roundtrip_fuzz():
    for i, buf in enumerate(fuzz_buffers()):
        codes = wc.encode_np(buf)
        # literals stay < 128, codes >= 128, never longer than the input
        assert codes.shape[0] <= max(buf.shape[0], 1)
        out = wc.decode_np(codes, buf.shape[0])
        np.testing.assert_array_equal(out, buf, err_msg=f"buffer {i}")


def test_c_encoder_matches_numpy_ground_truth():
    from twtml_tpu.features import native

    if not native.available():
        pytest.skip("no native library on this host")
    for i, buf in enumerate(fuzz_buffers(rounds=60, seed=1)):
        np.testing.assert_array_equal(
            wc.encode(buf), wc.encode_np(buf), err_msg=f"buffer {i}"
        )


def test_greedy_is_maximal_munch():
    """The vectorized run-parity encode must equal the sequential greedy
    definition — checked against a literal Python reference loop."""
    lut = wc.pair_lut()

    def reference(buf):
        out, i, n = [], 0, buf.shape[0]
        while i < n:
            if i + 1 < n:
                c = lut[(int(buf[i]) << 8) | int(buf[i + 1])]
                if c != 0xFF:
                    out.append(wc.CODE_BASE + int(c))
                    i += 2
                    continue
            out.append(int(buf[i]))
            i += 1
        return np.array(out, np.uint8).reshape(-1)

    for buf in fuzz_buffers(rounds=25, seed=2):
        np.testing.assert_array_equal(wc.encode_np(buf), reference(buf))


def test_jit_decode_matches_host_twin():
    from twtml_tpu.ops.ragged import units_from_codes

    for buf in fuzz_buffers(rounds=10, seed=3):
        if buf.shape[0] == 0:
            continue
        codes = wc.encode_np(buf)
        dev = jax.jit(
            lambda c, n=buf.shape[0]: units_from_codes(c, n)
        )(jnp.asarray(codes))
        np.testing.assert_array_equal(np.asarray(dev), buf)


def test_dictionary_is_frozen_shape():
    lut, table = wc.pair_lut(), wc.decode_table()
    assert lut.shape == (65536,) and lut.dtype == np.uint8
    assert table.shape == (wc.CODE_BASE, 2) and table.dtype == np.uint8
    # every dictionary pair is ASCII and round-trips through the LUT
    hits = np.nonzero(lut != 0xFF)[0]
    assert hits.shape[0] == wc.CODE_BASE
    assert int(lut[0]) == 0  # the zero pair is entry 0 (the bucket tail)


# ---------------------------------------------------------------------------
# packed layouts: byte parity on every path


def both_unpacks(pb):
    """(host unpack, in-jit unpack) of one packed wire."""
    host = unpack_batch(pb.buffer, pb.layout)
    dev = jax.jit(
        lambda buf: tuple(
            getattr(unpack_batch(buf, pb.layout), f)
            for f in ("units", "offsets", "numeric", "label", "mask")
        )
    )(jnp.asarray(pb.buffer))
    return host, dev


def test_pack_batch_codec_byte_parity():
    rb = ragged_batch(synthetic())
    assert rb.units.dtype == np.uint8
    raw = pack_batch(rb)
    coded = pack_batch(rb, codec="dict")
    assert coded.buffer.nbytes < raw.buffer.nbytes
    host, dev = both_unpacks(coded)
    assert_ragged_equal(host, rb)
    for f, arr in zip(("units", "offsets", "numeric", "label", "mask"), dev):
        got = np.asarray(arr)
        want = np.asarray(getattr(rb, f))
        assert np.dtype(got.dtype) == np.dtype(want.dtype), f
        np.testing.assert_array_equal(got, want, err_msg=f)


def test_pack_sharded_codec_byte_parity():
    rb = ragged_batch(synthetic())
    for s in (1, 2, 4):
        al = align_ragged_shards(rb, s)
        raw = pack_ragged_sharded(al)
        coded = pack_ragged_sharded(al, codec="dict")
        assert coded.buffer.nbytes <= raw.buffer.nbytes
        assert_ragged_equal(unpack_batch(coded.buffer, coded.layout), al)
        # the device-side unpack sees ONE shard segment (the shard_map
        # local slice): decode each slice and reassemble
        per_seg = coded.buffer.shape[0] // s
        al_units = np.asarray(al.units).reshape(s, -1)
        for seg in range(s):
            sl = coded.buffer[seg * per_seg : (seg + 1) * per_seg]
            local = jax.jit(
                lambda buf: unpack_batch(buf, coded.layout).units
            )(jnp.asarray(sl))
            np.testing.assert_array_equal(np.asarray(local), al_units[seg])


def test_pack_group_codec_byte_parity():
    statuses = synthetic(192)
    parts = [
        ragged_batch(statuses[i * 64 : (i + 1) * 64], rows=64, unit_bucket=64)
        for i in range(3)
    ]
    if len({(p.units.shape, p.row_len) for p in parts}) != 1:
        pytest.skip("synthetic batches landed in different unit buckets")
    stacked = stack_batches(parts)
    raw = pack_ragged_group(parts)
    coded = pack_ragged_group(parts, codec="dict")
    assert coded.buffer.nbytes < raw.buffer.nbytes
    assert_ragged_equal(unpack_batch(coded.buffer, coded.layout), stacked)
    dev = jax.jit(lambda buf: unpack_batch(buf, coded.layout).units)(
        jnp.asarray(coded.buffer)
    )
    np.testing.assert_array_equal(np.asarray(dev), np.asarray(stacked.units))


def test_uint16_units_ship_raw():
    """Non-ASCII-widened (uint16) units are ineligible — the metadata
    gate, like the int32 offset fallback: the layout records no codec."""
    statuses = synthetic()
    for s in statuses:
        if s.retweeted_status is not None:
            s.retweeted_status.text = "héllo wörld " + s.retweeted_status.text
    rb = ragged_batch(statuses)
    assert rb.units.dtype == np.uint16
    coded = pack_batch(rb, codec="dict")
    from twtml_tpu.features.batch import _layout_codec

    assert _layout_codec(coded.layout) is None
    assert_ragged_equal(unpack_batch(coded.buffer, coded.layout), rb)


def test_incompressible_batch_ships_raw():
    """A units buffer with ~no dictionary hits must keep the raw layout
    (the bucketed encoding would not shrink the wire)."""
    rng = np.random.default_rng(5)
    n, b = 4096, 32
    units = rng.integers(1, 128, n).astype(np.uint8)
    # kill accidental pair hits so the stream is truly incompressible
    lut = wc.pair_lut()
    hit = lut[(units[:-1].astype(np.uint16) << 8) | units[1:]] != 0xFF
    while hit.any():
        units[np.nonzero(hit)[0]] = rng.integers(1, 128, int(hit.sum()))
        hit = lut[(units[:-1].astype(np.uint16) << 8) | units[1:]] != 0xFF
    offsets = np.linspace(0, n, b + 1).astype(np.int32)
    rb = RaggedUnitBatch(
        units, offsets,
        np.zeros((b, 4), np.float32), np.zeros((b,), np.float32),
        np.ones((b,), np.float32), row_len=256,
    )
    coded = pack_batch(rb, codec="dict")
    from twtml_tpu.features.batch import _layout_codec

    assert _layout_codec(coded.layout) is None
    assert_ragged_equal(unpack_batch(coded.buffer, coded.layout), rb)


def test_empty_and_tiny_batches():
    """All-padding and single-row batches ride the codec like any other —
    the zero tail is the dictionary's entry 0 and compresses 2x."""
    feat = Featurizer(now_ms=NOW)
    empty = feat.featurize_batch_ragged([], row_bucket=32)
    one = ragged_batch(synthetic(4)[:1], rows=32)
    for rb in (empty, one):
        coded = pack_batch(rb, codec="dict")
        host, _dev = both_unpacks(coded)
        assert_ragged_equal(host, rb)


def test_oversized_rows_roundtrip():
    statuses = synthetic(16)
    for s in statuses:
        if s.retweeted_status is not None:
            s.retweeted_status.text = (
                s.retweeted_status.text + " padding words" * 200
            )
    rb = ragged_batch(statuses, rows=16)
    coded = pack_batch(rb, codec="dict")
    host, _ = both_unpacks(coded)
    assert_ragged_equal(host, rb)


def test_pack_fuzz_seeded():
    """Seeded fuzz over synthetic streams × shard counts × codec on/off:
    the unpacked view must always equal the pre-pack batch."""
    for seed in (11, 23, 47):
        rb = ragged_batch(synthetic(96, seed=seed), rows=32)
        for s in (1, 2, 4):
            al = align_ragged_shards(rb, s)
            pb = pack_ragged_sharded(al, codec="dict")
            assert_ragged_equal(unpack_batch(pb.buffer, pb.layout), al)


# ---------------------------------------------------------------------------
# model-level parity: the codec wire may never change the math


def test_model_trajectory_bitwise_identical():
    statuses = synthetic(192, seed=3)
    chunks = [statuses[i : i + 64] for i in range(0, 192, 64)]
    batches = [ragged_batch(c, rows=64, unit_bucket=64) for c in chunks]
    m_raw = StreamingLinearRegressionWithSGD(num_iterations=5, step_size=0.1)
    m_codec = StreamingLinearRegressionWithSGD(
        num_iterations=5, step_size=0.1
    )
    for b in batches:
        out_raw = m_raw.step(pack_batch(b))
        out_codec = m_codec.step(pack_batch(b, codec="dict"))
        for a, c in zip(out_raw, out_codec):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
    np.testing.assert_array_equal(
        m_raw.latest_weights, m_codec.latest_weights
    )


def test_mesh_sharded_model_bitwise_identical():
    """4-way data mesh: the codec-packed per-shard wire trains
    bit-identically to the raw packed wire (the shard_map body decodes
    its own segment in-program)."""
    from twtml_tpu.parallel import ParallelSGDModel, make_mesh

    statuses = synthetic(128, seed=9)
    chunks = [statuses[i : i + 64] for i in range(0, 128, 64)]
    batches = [ragged_batch(c, rows=64, unit_bucket=64) for c in chunks]
    mesh = make_mesh(num_data=4, devices=jax.devices()[:4])
    m_raw = ParallelSGDModel(mesh, num_iterations=5, step_size=0.1)
    m_codec = ParallelSGDModel(mesh, num_iterations=5, step_size=0.1)
    m_codec.wire_codec = "dict"
    for b in batches:
        out_raw = m_raw.step(m_raw.pack_for_wire(b))
        out_codec = m_codec.step(m_codec.pack_for_wire(b))
        for a, c in zip(out_raw, out_codec):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
    np.testing.assert_array_equal(
        m_raw.latest_weights, m_codec.latest_weights
    )


def test_scanned_group_wire_bitwise_identical():
    """step_many over the codec group wire == K sequential raw steps."""
    statuses = synthetic(192, seed=21)
    chunks = [statuses[i : i + 64] for i in range(0, 192, 64)]
    batches = [ragged_batch(c, rows=64, unit_bucket=64) for c in chunks]
    if len({(b.units.shape, b.row_len) for b in batches}) != 1:
        pytest.skip("synthetic batches landed in different unit buckets")
    m_seq = StreamingLinearRegressionWithSGD(num_iterations=5, step_size=0.1)
    m_grp = StreamingLinearRegressionWithSGD(num_iterations=5, step_size=0.1)
    for b in batches:
        m_seq.step(b)
    m_grp.step_many(pack_ragged_group(batches, codec="dict"))
    np.testing.assert_array_equal(m_seq.latest_weights, m_grp.latest_weights)


def test_tenant_group_wire_bitwise_identical():
    """The coalesced M-tenant wire with the codec on == codec off, bit for
    bit (stats and weights)."""
    from twtml_tpu.parallel.tenants import TenantStackModel

    statuses = synthetic(128, seed=31)
    batch = ragged_batch(statuses, rows=128)
    m_raw = TenantStackModel(3, wire_pack="group", num_iterations=5)
    m_codec = TenantStackModel(
        3, wire_pack="group", wire_codec="dict", num_iterations=5
    )
    out_raw = m_raw.step(batch)
    out_codec = m_codec.step(batch)
    for a, c in zip(out_raw, out_codec):
        if a is None:
            assert c is None
            continue
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
    np.testing.assert_array_equal(
        np.asarray(m_raw.latest_weights), np.asarray(m_codec.latest_weights)
    )


# ---------------------------------------------------------------------------
# telemetry + config surface


def test_wire_composition_reports_compressed_split():
    rb = ragged_batch(synthetic())
    raw_comp = wire_composition(pack_batch(rb))
    coded_comp = wire_composition(pack_batch(rb, codec="dict"))
    # "units" stays the RAW bytes (agrees with the unpacked view)...
    assert coded_comp["units"] == raw_comp["units"]
    assert coded_comp["offsets"] == raw_comp["offsets"]
    assert coded_comp["sideband"] == raw_comp["sideband"]
    # ...and the physical wire is the compressed size
    assert 0 < coded_comp["units_compressed"] < coded_comp["units"]
    assert "units_compressed" not in raw_comp


def test_codec_gauges_and_fallback_counter():
    from twtml_tpu.apps.common import _record_wire_codec
    from twtml_tpu.telemetry import metrics as _metrics

    reg = _metrics.get_registry()
    rb = ragged_batch(synthetic())
    before = reg.counter("wire.codec_fallbacks").value
    _record_wire_codec(pack_batch(rb, codec="dict"), "dict")
    assert reg.gauge("wire.codec_ratio").value > 1.0
    assert reg.gauge("wire.units_compressed_bytes").value > 0
    assert reg.counter("wire.codec_fallbacks").value == before
    # a raw wire that REQUESTED the codec counts as a fallback
    _record_wire_codec(pack_batch(rb), "dict")
    assert reg.counter("wire.codec_fallbacks").value == before + 1
    assert reg.gauge("wire.codec_ratio").value == 1.0


def test_config_flag_resolution():
    from twtml_tpu.config import ConfArguments

    conf = ConfArguments().parse(["--seconds", "0"])
    assert conf.wireCodec == "auto"
    assert conf.effective_wire_codec() == "off"  # auto = off, tunnel pending
    conf = ConfArguments().parse(["--seconds", "0", "--wireCodec", "dict"])
    assert conf.effective_wire_codec() == "dict"
    # dict + superbatch resolves the coalesced group wire
    assert conf.effective_wire_pack() == "group"
    # explicit stacked contradicts the codec — loud, not silent
    conf = ConfArguments().parse(
        ["--seconds", "0", "--wireCodec", "dict", "--wirePack", "stacked"]
    )
    with pytest.raises(ValueError, match="stacked contradicts"):
        conf.effective_wire_pack()
    # the codec needs the ragged raw-units wire
    conf = ConfArguments().parse(["--wireCodec", "dict", "--hashOn", "host"])
    with pytest.raises(ValueError, match="ragged"):
        conf.effective_wire_codec()
