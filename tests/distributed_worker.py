"""Worker process for the multi-host (jax.distributed) integration test.

Not a test module — launched by tests/test_distributed_multiprocess.py, two
processes forming a process group over localhost (gloo CPU collectives, 2
virtual devices each = 4 global). Each worker featurizes its shard of the
stream (the per-host sharded intake of SURVEY.md §7 stage 5), contributes
its rows to the global batch via host_local_batch_to_global, and runs one
mesh-sharded training step. Prints one JSON line with the step stats and
final weights.

Usage: python tests/distributed_worker.py <process_id> <num_processes> \
           <coordinator_port> <wire_format: unit|host> [mesh: 1d|2d]

``2d`` builds a (data=2, model=2) mesh over the 4 global devices — the
feature-sharded weight layout spanning PROCESS boundaries (each process
holds half of each weight shard pair).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
from twtml_tpu.utils.backend import set_cpu_device_count_hint  # noqa: E402

set_cpu_device_count_hint(2)  # jax_num_cpu_devices or XLA_FLAGS fallback


def main() -> None:
    pid, nprocs, port, wire = (
        int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
    )
    mesh_kind = sys.argv[5] if len(sys.argv) > 5 else "1d"
    if mesh_kind == "elastic_count":
        # ISSUE 13 acceptance (the PR 1/5 law re-asserted for the
        # membership plane): a REAL two-process lockstep run with the
        # elastic membership plane ACTIVE and membership columns riding
        # every tick. The cadence allgather count must equal the tick
        # count (the columns widened the payload, never the call count)
        # and jax.device_get must fire once per dispatched batch (zero
        # added host fetches). Formation goes through the ElasticRuntime
        # itself, so the counted run exercises the real detection-disabled
        # clients — not a stand-in.
        import jax.experimental.multihost_utils as mh

        from twtml_tpu.apps.common import FetchPipeline
        from twtml_tpu.features.featurizer import Featurizer
        from twtml_tpu.models import StreamingLinearRegressionWithSGD
        from twtml_tpu.parallel import elastic as _elastic
        from twtml_tpu.streaming.context import StreamingContext
        from twtml_tpu.streaming.membership import MembershipPlane
        from twtml_tpu.streaming.sources import ShardedSource, SyntheticSource
        from twtml_tpu.telemetry import metrics as _metrics

        runtime = _elastic.install_runtime("127.0.0.1", port, pid)
        runtime.form(0, list(range(nprocs)))

        counts = {"allgather": 0, "get": 0}
        real_ag = mh.process_allgather

        def counting_ag(arr, **kw):
            counts["allgather"] += 1
            return real_ag(arr, **kw)

        mh.process_allgather = counting_ag
        real_get = jax.device_get

        def counting_get(x):
            counts["get"] += 1
            return real_get(x)

        jax.device_get = counting_get

        model = StreamingLinearRegressionWithSGD(
            num_iterations=5, step_size=0.005
        )
        ssc = StreamingContext(batch_interval=0)
        stream = ssc.source_stream(
            ShardedSource(
                SyntheticSource(total=192, seed=7, base_ms=1785320000000),
                pid, nprocs,
            ),
            Featurizer(now_ms=1785320000000),
            row_bucket=16, token_bucket=64, row_multiple=2,
            device_hash=True,
        )
        transitions: list = []
        ssc.membership = MembershipPlane(
            runtime,
            lambda clean: transitions.append(("detach", clean)),
            lambda plan, reason: transitions.append(("attach", reason)),
        )
        pipe = FetchPipeline(
            model, lambda out, b, t, at_boundary: None, deterministic=True,
        )
        stream.foreach_batch(pipe.on_batch)
        ssc.start(lockstep=True)
        terminated = ssc.await_termination(timeout=120)
        ssc.stop()
        pipe.flush()
        reg = _metrics.get_registry().snapshot()
        print(json.dumps({
            "process": pid,
            "terminated": bool(terminated),
            "failed": bool(ssc.failed),
            "batches": int(ssc.batches_processed),
            "ticks": int(reg["counters"].get("lockstep.ticks", 0)),
            "allgathers": counts["allgather"],
            "device_gets": counts["get"],
            "fetch_count": int(reg["counters"].get("fetch.count", 0)),
            "epoch": runtime.epoch,
            "members": runtime.members,
            "transitions": transitions,
        }), flush=True)
        sys.stdout.flush()
        # elastic processes always leave hard (parallel/elastic.py): the
        # custom clients never run the shutdown barrier, so interpreter
        # teardown could trip the leaked-service poll FATAL
        runtime.finalize_exit(0)
        return
    jax.distributed.initialize(
        f"127.0.0.1:{port}", num_processes=nprocs, process_id=pid
    )

    if mesh_kind == "sideband":
        # fleet observability (ISSUE 5): a REAL two-process lockstep run
        # with host 1 artificially delayed via --chaos step:delay (the
        # injection sits INSIDE the dispatch timing window, so the stall
        # attributes to the upload stage). Both hosts gather the same
        # sideband matrix on the one cadence allgather; both must name
        # host 1 as the straggler. The allgather itself is counted so the
        # test proves the sideband added NO collective, and jax.device_get
        # is counted so it proves no added host fetch.
        #
        # The per-host model is deliberately HOST-LOCAL (no collectives in
        # the step): on this test's CPU backend collective execution is
        # synchronous, so a stall on one host would spread into every
        # peer's dispatch wall time through the in-step rendezvous and no
        # skew could be observed (on the real async-dispatch transport the
        # wait happens on device instead). A collective-free step keeps
        # each host's stage clocks its own, and makes the cadence
        # allgather the ONLY collective in the loop — exactly what the
        # zero-added-collectives count asserts against.
        import jax.experimental.multihost_utils as mh

        from twtml_tpu.apps.common import FetchPipeline
        from twtml_tpu.features.featurizer import Featurizer
        from twtml_tpu.models import StreamingLinearRegressionWithSGD
        from twtml_tpu.streaming import faults as _faults
        from twtml_tpu.streaming.context import StreamingContext
        from twtml_tpu.streaming.sources import ShardedSource, SyntheticSource
        from twtml_tpu.telemetry import metrics as _metrics
        from twtml_tpu.telemetry import sideband as _sideband

        if pid == 1:
            _faults.install_chaos("step:delay=0.12")

        counts = {"allgather": 0, "get": 0}
        real_ag = mh.process_allgather

        def counting_ag(arr):
            counts["allgather"] += 1
            return real_ag(arr)

        mh.process_allgather = counting_ag
        real_get = jax.device_get

        def counting_get(x):
            counts["get"] += 1
            return real_get(x)

        jax.device_get = counting_get

        model = StreamingLinearRegressionWithSGD(
            num_iterations=5, step_size=0.005
        )

        ssc = StreamingContext(batch_interval=0)
        stream = ssc.source_stream(
            ShardedSource(
                SyntheticSource(total=192, seed=7, base_ms=1785320000000),
                pid, nprocs,
            ),
            Featurizer(now_ms=1785320000000),
            row_bucket=16, token_bucket=64, row_multiple=2,
            device_hash=True,
        )
        pipe = FetchPipeline(
            model, lambda out, b, t, at_boundary: None,
            deterministic=True,
        )
        stream.foreach_batch(pipe.on_batch)
        ssc.start(lockstep=True)
        terminated = ssc.await_termination(timeout=120)
        ssc.stop()
        pipe.flush()

        reg = _metrics.get_registry().snapshot()
        view = _sideband.last_hosts()
        print(json.dumps({
            "process": pid,
            "terminated": bool(terminated),
            "failed": bool(ssc.failed),
            "batches": int(ssc.batches_processed),
            "ticks": int(reg["counters"].get("lockstep.ticks", 0)),
            "allgathers": counts["allgather"],
            "device_gets": counts["get"],
            "fetch_count": int(reg["counters"].get("fetch.count", 0)),
            "straggler_host": int(
                reg["gauges"].get("lockstep.straggler_host", -2)
            ),
            "tick_skew_ms": float(
                reg["gauges"].get("lockstep.tick_skew_ms", 0.0)
            ),
            "view_straggler": view["straggler"] if view else None,
            "view_stage": view["stage"] if view else None,
            "num_hosts_seen": len(view["hosts"]) if view else 0,
        }), flush=True)
        return

    if mesh_kind in ("lockstep_abort", "peer_kill"):
        # the anti-hang machinery. lockstep_abort: host 1's batch handler
        # raises mid-run; its loop must broadcast abort so host 0 STOPS
        # (instead of stalling in its next collective), and BOTH mark the
        # run failed. peer_kill: host 1 dies HARD (os._exit — no abort
        # broadcast, no goodbye); host 0's next cadence allgather can then
        # never complete, and the lockstep peer watchdog
        # (TWTML_LOCKSTEP_TIMEOUT_S) must turn that into a loud failed
        # abort rather than an infinite collective hang.
        from twtml_tpu.features.featurizer import Featurizer
        from twtml_tpu.parallel import ParallelSGDModel, make_mesh
        from twtml_tpu.parallel.distributed import host_local_batch_to_global
        from twtml_tpu.streaming.context import StreamingContext
        from twtml_tpu.streaming.sources import ShardedSource, SyntheticSource

        mesh = make_mesh(num_data=len(jax.devices()), devices=jax.devices())
        model = ParallelSGDModel(mesh, num_iterations=5, step_size=0.005)
        ssc = StreamingContext(batch_interval=0)
        stream = ssc.source_stream(
            ShardedSource(
                SyntheticSource(total=256, seed=7, base_ms=1785320000000),
                pid, nprocs,
            ),
            Featurizer(now_ms=1785320000000),
            row_bucket=16, token_bucket=64, row_multiple=2,
            device_hash=True,
        )
        seen = {"n": 0}

        def on_batch(batch, t):
            seen["n"] += 1
            model.step(host_local_batch_to_global(batch, mesh))
            if pid == 1 and seen["n"] == 3:
                if mesh_kind == "peer_kill":
                    # hard kill AFTER this tick's dispatch: the peer's
                    # tick-3 collectives complete, so the hang host 0 must
                    # survive is the NEXT cadence allgather
                    os._exit(42)
                # post-dispatch handler failure: the recoverable class —
                # this host's collective program DID run, so the peer's
                # collectives complete and the abort flag can reach it on
                # the next tick. (A failure BEFORE dispatch deadlocks the
                # peer's in-order collective queue until runtime timeouts —
                # the documented unrecoverable class.)
                raise RuntimeError("injected handler failure on host 1")

        stream.foreach_batch(on_batch)
        ssc.start(lockstep=True)
        terminated = ssc.await_termination(timeout=60)
        ssc.stop()
        print(json.dumps({
            "process": pid,
            "terminated": bool(terminated),
            "failed": bool(ssc.failed),
            "batches_seen": seen["n"],
        }), flush=True)
        if mesh_kind == "peer_kill":
            # with a hard-dead peer, jax.distributed's atexit shutdown
            # barrier can never complete — its client FATALs the process
            # (SIGABRT) after the coordination-service timeout. The
            # watchdog behavior under test is fully reported above, so
            # skip the doomed barrier. (A real app exits non-zero via its
            # RuntimeError in exactly this state.)
            sys.stdout.flush()
            os._exit(0)
        return

    import numpy as np

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.parallel import ParallelSGDModel, make_mesh, shard_batch
    from twtml_tpu.parallel.distributed import host_local_batch_to_global
    from twtml_tpu.streaming.sources import SyntheticSource

    # base_ms pinned: the 2d topology device_puts the SAME global batch from
    # every process, which demands bit-identical featurization
    statuses = list(
        SyntheticSource(total=64, seed=7, base_ms=1785320000000).produce()
    )
    feat = Featurizer(now_ms=1785320000000)

    def featurize(sts):
        if wire == "unit":
            return feat.featurize_batch_units(
                sts, row_bucket=len(sts), unit_bucket=64, pre_filtered=True
            )
        return feat.featurize_batch(
            sts, row_bucket=len(sts), token_bucket=64, pre_filtered=True
        )

    if mesh_kind == "tenants":
        # ISSUE 7: the multi-tenant plane with the TENANT axis mapped onto
        # the cross-process MODEL axis — device order [p0d0,p1d0,p0d1,p1d1]
        # pairs processes on the model axis (as in '2d' below), so each
        # process addresses only HALF the tenants' weight shards and the
        # latest_weights/stats reads exercise the process_allgather path.
        # Tenants are independent (no collective crosses the model axis);
        # rows shard over 'data'. Both hosts featurize the SAME stream
        # (base_ms pinned) and device_put the same routed stacked wire.
        from twtml_tpu.parallel import TenantStackModel, make_mesh

        d = jax.devices()
        mesh = make_mesh(
            num_data=2, num_model=2, devices=[d[0], d[2], d[1], d[3]]
        )
        model = TenantStackModel(
            4, num_iterations=5, step_size=0.005, mesh=mesh
        )
        chunks = [statuses[:32], statuses[32:]]
        for sts in chunks:
            out = model.step(feat.featurize_batch_units(
                sts, row_bucket=32, unit_bucket=64, pre_filtered=True
            ))
        gather = TenantStackModel._to_host
        print(json.dumps({
            "process": pid,
            "tenant_counts": gather(out.count).tolist(),
            "tenant_mses": gather(out.mse).tolist(),
            "weights_addressable": bool(out.count.is_fully_addressable),
            "weights": np.asarray(model.latest_weights).tolist(),
        }), flush=True)
        return

    if mesh_kind == "2d_ckpt":
        # checkpoint round-trip on the cross-process feature-sharded layout:
        # step → gather (process_allgather: shards are NOT fully addressable
        # here) → pid 0 writes the .npz → barrier → BOTH processes restore
        # into a FRESH model (set_initial_weights materializes only local
        # shards via make_array_from_callback) → second step. Must equal an
        # uninterrupted 2-step run.
        from jax.experimental import multihost_utils

        from twtml_tpu.checkpoint import Checkpointer

        d = jax.devices()
        mesh = make_mesh(
            num_data=2, num_model=2, devices=[d[0], d[2], d[1], d[3]]
        )
        model = ParallelSGDModel(
            mesh, num_text_features=1000, num_iterations=5, step_size=0.005
        )
        global_batch = shard_batch(featurize(statuses), mesh)
        model.step(global_batch)
        ckpt = Checkpointer(os.environ["TWTML_CKPT_DIR"])
        gathered = model.latest_weights  # collective: every process calls it
        if pid == 0:
            ckpt.save(1, gathered, {"batches": 1})
        multihost_utils.sync_global_devices("ckpt-written")
        weights, meta = ckpt.restore()
        assert meta["batches"] == 1
        resumed = ParallelSGDModel(
            mesh, num_text_features=1000, num_iterations=5, step_size=0.005
        ).set_initial_weights(weights)
        assert not resumed._weights["text"].is_fully_addressable
        out = resumed.step(global_batch)
        print(json.dumps({
            "process": pid,
            "count": float(out.count),
            "mse": float(out.mse),
            "weights": np.asarray(resumed.latest_weights).tolist(),
        }), flush=True)
        return
    if mesh_kind == "2d_gram":
        # the Gram (dual) inner loop with BOTH of its collectives crossing
        # process boundaries: the batch all-gather over 'data' and the G
        # panel psum over 'model' (device order pairs processes on the model
        # axis, as in '2d' below). Must match the dense single-process math.
        d = jax.devices()
        mesh = make_mesh(
            num_data=2, num_model=2, devices=[d[0], d[2], d[1], d[3]]
        )
        model = ParallelSGDModel(
            mesh, num_text_features=1000, num_iterations=5, step_size=0.005,
            use_sparse=True, use_gram=True,
        )
        global_batch = shard_batch(featurize(statuses), mesh)
    elif mesh_kind == "2d":
        # arrange devices so the MODEL axis pairs devices from DIFFERENT
        # processes: jax.devices() is process-major [p0d0,p0d1,p1d0,p1d1];
        # ordering [p0d0,p1d0,p0d1,p1d1] makes each mesh row mix processes —
        # the model-axis psum rides the cross-process (DCN-analog) path and
        # each weight shard is NOT fully addressable from one process
        # (exercising the latest_weights allgather). With this topology the
        # DATA shards span both processes too, so per-host intake sharding
        # doesn't apply: every host supplies the full batch (device_put
        # places each device's local shard from it).
        d = jax.devices()
        mesh = make_mesh(
            num_data=2, num_model=2, devices=[d[0], d[2], d[1], d[3]]
        )
        model = ParallelSGDModel(
            mesh, num_text_features=1000, num_iterations=5, step_size=0.005
        )
        global_batch = shard_batch(featurize(statuses), mesh)
    else:
        mesh = make_mesh(num_data=len(jax.devices()), devices=jax.devices())
        model = ParallelSGDModel(mesh, num_iterations=5, step_size=0.005)
        local = statuses[pid::nprocs]  # this host's stream shard
        batch = featurize(local)
        global_batch = host_local_batch_to_global(batch, mesh)
    out = model.step(global_batch)
    print(json.dumps({
        "process": pid,
        "count": float(out.count),
        "mse": float(out.mse),
        "weights": np.asarray(model.latest_weights).tolist(),
    }), flush=True)


if __name__ == "__main__":
    main()
