"""Worker process for the multi-host (jax.distributed) integration test.

Not a test module — launched by tests/test_distributed_multiprocess.py, two
processes forming a process group over localhost (gloo CPU collectives, 2
virtual devices each = 4 global). Each worker featurizes its shard of the
stream (the per-host sharded intake of SURVEY.md §7 stage 5), contributes
its rows to the global batch via host_local_batch_to_global, and runs one
mesh-sharded training step. Prints one JSON line with the step stats and
final weights.

Usage: python tests/distributed_worker.py <process_id> <num_processes> \
           <coordinator_port> <wire_format: unit|host>
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.config.update("jax_num_cpu_devices", 2)


def main() -> None:
    pid, nprocs, port, wire = (
        int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
    )
    jax.distributed.initialize(
        f"127.0.0.1:{port}", num_processes=nprocs, process_id=pid
    )

    import numpy as np

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.parallel import ParallelSGDModel, make_mesh
    from twtml_tpu.parallel.distributed import host_local_batch_to_global
    from twtml_tpu.streaming.sources import SyntheticSource

    statuses = list(SyntheticSource(total=64, seed=7).produce())
    local = statuses[pid::nprocs]  # this host's stream shard
    feat = Featurizer(now_ms=1785320000000)
    if wire == "unit":
        batch = feat.featurize_batch_units(
            local, row_bucket=16, unit_bucket=64, pre_filtered=True
        )
    else:
        batch = feat.featurize_batch(
            local, row_bucket=16, token_bucket=64, pre_filtered=True
        )

    mesh = make_mesh(num_data=len(jax.devices()), devices=jax.devices())
    global_batch = host_local_batch_to_global(batch, mesh)
    model = ParallelSGDModel(mesh, num_iterations=5, step_size=0.005)
    out = model.step(global_batch)
    print(json.dumps({
        "process": pid,
        "count": float(out.count),
        "mse": float(out.mse),
        "weights": np.asarray(model.latest_weights).tolist(),
    }), flush=True)


if __name__ == "__main__":
    main()
