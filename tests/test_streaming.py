"""Streaming runtime tests: micro-batcher, source supervision, replay mode,
and the end-to-end linear-regression app on the tweet fixture (the reference
never tested this layer — SURVEY.md §4 notes the gap; BASELINE config #1 is
exactly this replayed-tweet run)."""

import os
import threading
import time

import numpy as np
import pytest

from twtml_tpu.config import ConfArguments
from twtml_tpu.features.featurizer import Featurizer, Status
from twtml_tpu.streaming.context import StreamingContext
from twtml_tpu.streaming.sources import QueueSource, ReplayFileSource, Source

DATA = os.path.join(os.path.dirname(__file__), "data", "tweets.jsonl")


def rt(label=500, text="some tweet text"):
    return Status(text="RT", retweeted_status=Status(text=text, retweet_count=label))


def test_wall_clock_batching():
    src = QueueSource()
    ssc = StreamingContext(batch_interval=0.1)
    feat = Featurizer(now_ms=0)
    seen = []
    ssc.source_stream(src, feat).foreach_batch(
        lambda batch, t: seen.append(batch.num_valid)
    )
    ssc.start()
    for _ in range(3):
        src.push(rt())
    time.sleep(0.25)
    src.close()
    ssc.await_termination(timeout=2)
    ssc.stop()
    assert sum(seen) == 3
    assert len(seen) >= 1


def test_outputs_fire_in_registration_order():
    src = QueueSource()
    ssc = StreamingContext(batch_interval=0.05)
    order = []
    stream = ssc.source_stream(src, Featurizer(now_ms=0))
    stream.foreach_batch(lambda b, t: order.append("stats"))
    stream.foreach_batch(lambda b, t: order.append("train"))
    src.push(rt())
    src.close()
    ssc.start()
    ssc.await_termination(timeout=2)
    ssc.stop()
    assert order[:2] == ["stats", "train"]


def test_source_supervision_restarts():
    class Flaky(Source):
        name = "flaky"
        attempts = 0

        def produce(self):
            Flaky.attempts += 1
            if Flaky.attempts == 1:
                raise RuntimeError("simulated receiver crash")
            yield rt()

    src = Flaky(restart_backoff=0.01)
    got = []
    src.start(got.append)
    deadline = time.time() + 2
    while not src.exhausted and time.time() < deadline:
        time.sleep(0.01)
    src.stop()
    assert Flaky.attempts == 2
    assert len(got) == 1


def test_source_gives_up_after_max_restarts():
    class Dead(Source):
        name = "dead"

        def produce(self):
            raise RuntimeError("always broken")
            yield  # pragma: no cover

    src = Dead(max_restarts=2, restart_backoff=0.01)
    src.start(lambda s: None)
    deadline = time.time() + 2
    while not src.exhausted and time.time() < deadline:
        time.sleep(0.01)
    assert src.exhausted
    src.stop()


def test_max_restarts_bounds_consecutive_failures_only():
    """A run that emitted data resets the restart ladder: a long-lived
    receiver must not die on its Nth lifetime disconnect (the live Twitter
    source raises on every server-side stream close by design)."""

    class DropsEveryTime(Source):
        name = "droppy"
        attempts = 0

        def produce(self):
            DropsEveryTime.attempts += 1
            yield rt()
            raise ConnectionError("disconnect after healthy streaming")

    src = DropsEveryTime(max_restarts=2, restart_backoff=0.001)
    got = []
    src.start(got.append)
    deadline = time.time() + 2
    while len(got) < 8 and time.time() < deadline:
        time.sleep(0.005)
    src.stop()
    # 8 successful emissions needs 8 connections: far more than
    # max_restarts=2, alive because every failure followed healthy output
    assert len(got) >= 8
    assert not src.exhausted


def test_replay_run_to_completion():
    src = ReplayFileSource(DATA)
    ssc = StreamingContext()
    feat = Featurizer(now_ms=0)
    batches = []
    ssc.source_stream(src, feat).foreach_batch(
        lambda batch, t: batches.append(batch)
    )
    n = ssc.run_to_completion()
    assert n == len(batches) >= 1
    assert sum(b.num_valid for b in batches) == 6  # 6 in-range retweets in fixture


def test_e2e_linear_app_on_replay(capsys):
    from twtml_tpu.apps.linear_regression import run

    conf = ConfArguments().parse([
        "--source", "replay",
        "--replayFile", DATA,
        "--seconds", "1",
        "--backend", "cpu",
        "--lightning", "http://127.0.0.1:9",  # closed port: exercises Try paths
        "--twtweb", "http://127.0.0.1:9",
    ])
    totals = run(conf)
    assert totals["count"] == 6
    assert totals["batches"] >= 1
    out = capsys.readouterr().out
    assert "count: 6" in out
    assert "mse:" in out


def test_feature_stream_device_hash_wire_format():
    """device_hash=True (the apps' default via --hashOn device) ships
    UnitBatches through the scheduler; stats surface matches host hashing."""
    from twtml_tpu.features.batch import UnitBatch

    results = {}
    for device_hash in (False, True):
        src = QueueSource()
        ssc = StreamingContext(batch_interval=0.05)
        feat = Featurizer(now_ms=0)
        batches = []
        ssc.source_stream(src, feat, device_hash=device_hash).foreach_batch(
            lambda b, t: batches.append(b)
        )
        for lab in (150, 300, 700):
            src.push(rt(label=lab, text=f"tweet number {lab}"))
        src.close()
        ssc.start()
        ssc.await_termination(timeout=2)
        ssc.stop()
        assert sum(b.num_valid for b in batches) == 3
        results[device_hash] = batches
    assert all(isinstance(b, UnitBatch) for b in results[True])

    def labels(batches):
        return sorted(
            float(l) for b in batches for l in b.label[b.mask.astype(bool)]
        )

    assert labels(results[True]) == [150.0, 300.0, 700.0]
    assert labels(results[False]) == labels(results[True])


def test_bucket_overflow_warns_once(caplog):
    """A tweet longer than the pinned tokenBucket grows the shape; the
    stream warns once so a defeated compile warmup is visible."""
    import logging

    from twtml_tpu.streaming.context import FeatureStream

    stream = FeatureStream(
        Featurizer(now_ms=0), row_bucket=8, token_bucket=8, device_hash=True
    )
    long_tweet = rt(text="x" * 100)
    with caplog.at_level(logging.WARNING, logger="twtml.streaming.context"):
        stream._process([long_tweet], 0.0)
        stream._process([long_tweet], 0.0)
    warnings = [r for r in caplog.records if "overflowed" in r.message]
    assert len(warnings) == 1


def test_block_fill_gate_counts_rows_not_items():
    """Regression (ADVICE r2): the --seconds 0 fill gate must compare queued
    ROWS to the row bucket. Each block item is many rows; an item-count gate
    never fills, so the scheduler buffers the ENTIRE stream before the first
    batch — this source deadlocks (then times out) unless a batch runs while
    it is still producing."""
    from twtml_tpu.features.blocks import ParsedBlock

    def block(rows):
        units = np.tile(
            np.frombuffer(b"ab", np.uint8).astype(np.uint16), rows
        )
        numeric = np.zeros((rows, 5), np.int64)
        numeric[:, 0] = 500  # label within the default retweet interval
        return ParsedBlock(
            numeric,
            units,
            np.arange(rows + 1, dtype=np.int64) * 2,
            np.ones((rows,), np.uint8),
        )

    batch_done = threading.Event()

    class GatedBlocks(Source):
        name = "gated-blocks"

        def produce(self):
            yield block(64)
            yield block(64)
            # 128 rows (= the bucket) are queued as TWO items: the scheduler
            # must batch them while this source is still alive
            assert batch_done.wait(5.0), "no batch while source alive"
            yield block(64)

    ssc = StreamingContext(batch_interval=0)
    stream = ssc.source_stream(
        GatedBlocks(max_restarts=0), Featurizer(now_ms=0),
        row_bucket=128, token_bucket=16,
    )
    seen = []

    def on_batch(batch, t):
        seen.append(int(batch.mask.sum()))
        batch_done.set()

    stream.foreach_batch(on_batch)
    ssc.start()
    assert ssc.await_termination(timeout=15)
    ssc.stop()
    assert seen[0] == 128 and sum(seen) == 192


def test_steady_state_stream_compiles_exactly_once():
    """Shape discipline guard: with pinned buckets, N same-shaped batches
    must reuse ONE compiled train-step program — recompile churn is this
    design's key perf regression class (SURVEY.md §7 hard part (a))."""
    import logging

    from twtml_tpu.models import StreamingLinearRegressionWithSGD

    compiles: list[str] = []

    class Capture(logging.Handler):
        def emit(self, record):
            msg = record.getMessage()
            if "Finished XLA compilation" in msg:
                compiles.append(msg)

    handler = Capture()
    logger = logging.getLogger("jax._src.dispatch")
    prev_level = logger.level
    logger.addHandler(handler)
    # DEBUG on this logger is sufficient to receive the compile records;
    # the global jax_log_compiles flag is deliberately left untouched
    logger.setLevel(logging.DEBUG)
    try:
        feat = Featurizer(now_ms=0)
        model = StreamingLinearRegressionWithSGD(num_iterations=5)
        for i in range(6):
            batch = feat.featurize_batch_units(
                [rt(label=100 + i, text=f"steady state tweet {i} " * (i + 1))],
                row_bucket=8, unit_bucket=128, pre_filtered=True,
            )
            model.step(batch)
        step_compiles = [m for m in compiles if "train_step" in m]
        assert len(step_compiles) == 1, step_compiles
    finally:
        logger.removeHandler(handler)
        logger.setLevel(prev_level)
