"""Metrics registry + tunnel-health classifier (telemetry/metrics.py):
counter/gauge/histogram semantics, snapshot isolation, and health-phase
transitions on synthetic latency series — the observability layer's
contracts, independent of any pipeline."""

import threading

from twtml_tpu.telemetry.metrics import (
    MetricsRegistry,
    TunnelHealthMonitor,
)


def test_counter_gauge_semantics():
    reg = MetricsRegistry()
    c = reg.counter("pipeline.batches")
    c.inc()
    c.inc(4)
    assert c.snapshot() == 5
    # get-or-create: same underlying metric
    assert reg.counter("pipeline.batches") is c
    g = reg.gauge("fetch.queue_depth")
    g.set(3)
    g.add(2)
    g.set(7)  # set wins over accumulated state
    assert g.snapshot() == 7


def test_histogram_semantics():
    reg = MetricsRegistry()
    h = reg.histogram("fetch.latency_s")
    for v in (0.001, 0.002, 0.004, 0.1, 2.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 5
    assert abs(snap["sum"] - 2.107) < 1e-9
    assert snap["min"] == 0.001 and snap["max"] == 2.0
    assert abs(snap["mean"] - 2.107 / 5) < 1e-9
    # bucket counts only for touched buckets
    assert sum(c for _, c in snap["buckets"]) == 5
    # percentile estimator: median lands at the 0.004 bucket's bound
    assert 0.002 <= h.percentile(0.5) <= 0.008
    assert h.percentile(1.0) >= 2.0


def test_histogram_snapshot_derived_percentiles_match_percentile():
    """r8: /api/metrics ships derived p50/p95/p99 per histogram — the
    snapshot values must be exactly what Histogram.percentile computes
    (one shared bucket walk), including the empty and overflow cases."""
    reg = MetricsRegistry()
    h = reg.histogram("fetch.latency_s")
    assert h.snapshot()["p50"] == 0.0  # empty: all quantiles zero
    import random

    rnd = random.Random(7)
    for _ in range(500):
        h.observe(rnd.uniform(0.001, 4.0))
    snap = h.snapshot()
    for key, p in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
        assert snap[key] == h.percentile(p), key
    assert snap["p50"] <= snap["p95"] <= snap["p99"]
    # overflow tail: quantiles beyond the last bound report the true max
    h2 = reg.histogram("stall_s")
    for v in (1000.0, 2000.0, 3000.0):
        h2.observe(v)
    assert h2.snapshot()["p99"] == 3000.0


def test_snapshot_isolation():
    reg = MetricsRegistry()
    reg.counter("a").inc(2)
    reg.gauge("b").set(1)
    reg.histogram("h").observe(0.5)
    snap = reg.snapshot()
    reg.counter("a").inc(10)
    reg.gauge("b").set(9)
    reg.histogram("h").observe(0.5)
    # the snapshot taken earlier is immune to later mutation
    assert snap["counters"]["a"] == 2
    assert snap["gauges"]["b"] == 1
    assert snap["histograms"]["h"]["count"] == 1


def test_counter_thread_safety():
    reg = MetricsRegistry()
    c = reg.counter("x")

    def worker():
        for _ in range(1000):
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.snapshot() == 8000


# ---------------------------------------------------------------------------
# health-phase classifier on synthetic latency series


def test_health_steady_rtt_stays_healthy():
    reg = MetricsRegistry()
    mon = TunnelHealthMonitor(registry=reg)
    for i in range(50):
        mon.observe(0.07 + 0.005 * (i % 3), now=float(i))
    assert mon.phase == TunnelHealthMonitor.HEALTHY
    assert mon.transitions == []
    assert mon.observations["degraded"] == 0


def test_health_degrades_and_recovers():
    reg = MetricsRegistry()
    mon = TunnelHealthMonitor(registry=reg)
    t = iter(range(1000))
    for _ in range(20):  # healthy baseline ~70 ms
        mon.observe(0.07, now=float(next(t)))
    assert mon.phase == TunnelHealthMonitor.HEALTHY
    for _ in range(20):  # stall burst: 600 ms medians
        mon.observe(0.6, now=float(next(t)))
    assert mon.phase == TunnelHealthMonitor.DEGRADED
    for _ in range(40):  # back to RTT scale
        mon.observe(0.07, now=float(next(t)))
    assert mon.phase == TunnelHealthMonitor.HEALTHY
    phases = [p for _, p in mon.transitions]
    assert phases == ["degraded", "healthy"]
    # transition count landed in the registry too
    assert reg.counter("tunnel.phase_transitions").snapshot() == 2
    assert mon.observations["degraded"] > 0
    summary = mon.summary()
    assert summary["phase"] == "healthy" and summary["transitions"] == 2
    assert summary["best_ms"] == 70.0


def test_health_floor_keeps_cpu_jitter_healthy():
    """µs-scale latencies (CPU backend, fake models) sit far below tunnel-RTT
    scale: relative jitter there must never classify as degraded."""
    mon = TunnelHealthMonitor(registry=MetricsRegistry())
    for i in range(100):
        mon.observe(1e-6 if i % 2 else 2e-5, now=float(i))  # 20x swings
    assert mon.phase == TunnelHealthMonitor.HEALTHY
    assert mon.transitions == []


def test_health_hysteresis_no_flap_on_single_outlier():
    mon = TunnelHealthMonitor(registry=MetricsRegistry())
    for i in range(30):
        mon.observe(0.07, now=float(i))
    mon.observe(5.0, now=31.0)  # one stalled fetch
    # a single outlier does not move the rolling median past the threshold
    assert mon.phase == TunnelHealthMonitor.HEALTHY
    assert mon.transitions == []
