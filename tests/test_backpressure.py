"""Bounded ingest backpressure (ISSUE 4 tentpole, part 1): the intake queue
was the pipeline's last unbounded buffer — a source burst or a slow tunnel
phase grew host RSS without limit. `--maxQueueRows` bounds it by ROW count
with two policies (block: producers wait; shed-oldest: oldest rows drop,
counted), `--shedPolicy` picks one, and the parity law holds on survivors:
shedding from the FRONT never reorders the rows that remain."""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from twtml_tpu.config import ConfArguments
from twtml_tpu.streaming import faults
from twtml_tpu.streaming.context import _RowCountQueue
from twtml_tpu.telemetry import metrics as _metrics


@pytest.fixture(autouse=True)
def clean_state():
    _metrics.reset_for_tests()
    faults.uninstall_chaos()
    yield
    faults.uninstall_chaos()
    _metrics.reset_for_tests()


def _block_item(rows: int, tag: int = 0):
    return SimpleNamespace(rows=rows, tag=tag)


# -- queue semantics ---------------------------------------------------------

def test_unbounded_queue_is_the_pre_r7_path():
    q = _RowCountQueue()
    for i in range(100):
        q.put(i)
    assert q.rows_queued == 100
    assert [q.get_nowait() for _ in range(100)] == list(range(100))


def test_block_policy_blocks_producer_at_the_row_bound():
    q = _RowCountQueue()
    q.configure_bound(10, "block")
    for i in range(10):
        q.put(i)
    landed = threading.Event()

    def producer():
        q.put(10)  # over the bound: must wait for a drain
        landed.set()

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    assert not landed.wait(0.25), "producer sailed past the row bound"
    assert q.rows_queued == 10
    q.get_nowait()  # consumer drains one row -> bound has room
    assert landed.wait(2.0), "producer never released after the drain"
    assert q.rows_queued == 10
    # FIFO order end to end: nothing reordered by the wait
    assert [q.get_nowait() for _ in range(10)] == list(range(1, 11))


def test_block_policy_admits_oversized_item_alone():
    """One item larger than the whole bound must pass when the queue is
    empty — blocking it forever would deadlock the stream on one big
    block."""
    q = _RowCountQueue()
    q.configure_bound(4, "block")
    q.put(_block_item(100))  # admitted: queue was empty
    assert q.rows_queued == 100


def test_close_releases_a_blocked_producer():
    q = _RowCountQueue()
    q.configure_bound(2, "block")
    q.put(0)
    q.put(1)
    released = threading.Event()

    def producer():
        q.put(2)
        released.set()

    threading.Thread(target=producer, daemon=True).start()
    assert not released.wait(0.2)
    q.close()  # shutdown: consumer is gone, producer must not wedge
    assert released.wait(2.0)


def test_shed_oldest_sheds_counted_and_never_reorders_survivors():
    """Parity law: predict-then-train ordering must hold on the SURVIVING
    rows — shed-oldest drops from the queue front, so whatever remains is
    a contiguous, in-order suffix of the input."""
    q = _RowCountQueue()
    q.configure_bound(8, "shed-oldest")
    for i in range(20):
        q.put(i)
    assert q.rows_queued <= 8
    survivors = []
    while True:
        try:
            survivors.append(q.get_nowait())
        except Exception:
            break
    # differential: the survivors are EXACTLY the input's tail, in order
    assert survivors == list(range(20 - len(survivors), 20))
    shed = 20 - len(survivors)
    assert shed > 0
    assert q.rows_shed_total == shed
    assert _metrics.get_registry().counter(
        "ingest.rows_shed").snapshot() == shed


def test_shed_oldest_counts_block_rows_not_items():
    q = _RowCountQueue()
    q.configure_bound(100, "shed-oldest")
    q.put(_block_item(60, tag=0))
    q.put(_block_item(40, tag=1))
    q.put(_block_item(30, tag=2))  # 130 > 100: sheds the 60-row block
    assert q.rows_queued == 70
    assert q.rows_shed_total == 60
    assert [it.tag for it in (q.get_nowait(), q.get_nowait())] == [1, 2]


def test_putback_is_exempt_from_the_bound():
    """The drain splitter's remainder was already admitted once; bouncing
    it would lose rows mid-drain."""
    q = _RowCountQueue()
    q.configure_bound(4, "shed-oldest")
    for i in range(4):
        q.put(i)
    q.putback(_block_item(100))
    assert q.rows_queued == 104
    assert q.rows_shed_total == 0
    assert q.get_nowait().rows == 100  # and it comes out FIRST


def test_bad_policy_rejected():
    q = _RowCountQueue()
    with pytest.raises(ValueError):
        q.configure_bound(8, "newest-first")


# -- config resolution -------------------------------------------------------

def test_effective_max_queue_rows_resolution():
    conf = ConfArguments().parse(["--batchBucket", "256"])
    assert conf.effective_max_queue_rows() == 8 * 256  # auto: 8 buckets
    conf = ConfArguments().parse(["--batchBucket", "256",
                                  "--maxQueueRows", "1000"])
    assert conf.effective_max_queue_rows() == 1000  # explicit wins
    conf = ConfArguments().parse(["--batchBucket", "256",
                                  "--maxQueueRows", "-1"])
    assert conf.effective_max_queue_rows() == 0  # explicitly unbounded
    conf = ConfArguments().parse([])
    assert conf.effective_max_queue_rows() == 0  # no bucket: nothing to size from
    with pytest.raises(SystemExit):
        ConfArguments().parse(["--shedPolicy", "newest"])


# -- backoff jitter + restart visibility (satellite) -------------------------

def test_backoff_is_jittered_and_capped():
    from twtml_tpu.streaming.sources import Source

    src = Source(restart_backoff=1.0)
    for restarts in (1, 3, 8, 200):
        ladder = min(1.0 * 2 ** min(restarts - 1, 12), Source.BACKOFF_CAP_S)
        samples = {src._backoff(RuntimeError(), restarts) for _ in range(32)}
        assert all(0.5 * ladder <= s <= ladder for s in samples)
        assert all(s <= Source.BACKOFF_CAP_S for s in samples)
    # jitter actually varies (decorrelates restart storms)
    assert len({src._backoff(RuntimeError(), 4) for _ in range(32)}) > 1


def test_source_restarts_are_registry_state():
    from twtml_tpu.streaming.sources import Source

    class Flaky(Source):
        name = "flaky-test"

        def __init__(self, **kw):
            super().__init__(**kw)
            self.runs = 0

        def produce(self):
            self.runs += 1
            yield SimpleNamespace(rows=1)
            if self.runs < 3:
                raise ConnectionError("boom")

    src = Flaky(max_restarts=5, restart_backoff=0.001)
    got = []
    src.start(got.append)
    deadline = time.time() + 5.0
    while not src.exhausted and time.time() < deadline:
        time.sleep(0.01)
    src.stop()
    assert src.exhausted
    reg = _metrics.get_registry()
    assert reg.counter("source.restarts").snapshot() == 2
    assert reg.counter("source.flaky-test.restarts").snapshot() == 2


# -- end-to-end: the bounded queue under the real app ------------------------

CLOSED = "http://127.0.0.1:9"


def _write_replay(path, total, seed):
    import json

    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    with open(path, "w") as fh:
        for s in SyntheticSource(
            total=total, seed=seed, base_ms=1785320000000
        ).produce():
            fh.write(json.dumps(_status_json(s)) + "\n")


def test_app_block_policy_trains_every_row(tmp_path):
    """block (the default policy): a replay producer far ahead of the
    consumer waits at the bound instead of ballooning the queue — and no
    row is ever lost."""
    import jax

    from twtml_tpu.apps import linear_regression as app

    jax.devices()
    path = tmp_path / "tweets.jsonl"
    _write_replay(path, 8 * 16, seed=41)
    totals = app.run(ConfArguments().parse([
        "--source", "replay", "--replayFile", str(path),
        "--seconds", "0", "--backend", "cpu",
        "--batchBucket", "16", "--tokenBucket", "64",
        "--master", "local[1]",
        "--maxQueueRows", "32",
        "--lightning", CLOSED, "--twtweb", CLOSED, "--webTimeout", "0.2",
    ]))
    assert totals["count"] == 8 * 16
    assert _metrics.get_registry().counter("ingest.rows_shed").snapshot() == 0


def test_app_shed_oldest_accounting_closes(tmp_path):
    """shed-oldest under a source.burst rate spike: every emitted row is
    either trained or counted as shed — the loss is visible, never
    silent. (The burst re-emits the current status N extra times, so
    emitted = replayed + N x firings.)"""
    import jax

    from twtml_tpu.apps import linear_regression as app

    jax.devices()
    path = tmp_path / "tweets.jsonl"
    n = 8 * 16
    _write_replay(path, n, seed=42)
    totals = app.run(ConfArguments().parse([
        "--source", "replay", "--replayFile", str(path),
        "--seconds", "0", "--backend", "cpu",
        "--batchBucket", "16", "--tokenBucket", "64",
        "--master", "local[1]",
        "--maxQueueRows", "32", "--shedPolicy", "shed-oldest",
        "--chaos", "source.burst:rows=8@16",
        "--lightning", CLOSED, "--twtweb", CLOSED, "--webTimeout", "0.2",
    ]))
    reg = _metrics.get_registry()
    firings = reg.counter("chaos.source.burst.injected").snapshot()
    shed = reg.counter("ingest.rows_shed").snapshot()
    assert firings > 0
    emitted = n + 8 * firings
    assert totals["count"] + shed == emitted
    # the queue never held more than the bound (modulo the one item being
    # admitted); the gauge is per-drain so just check it stayed bounded
    assert reg.gauge("ingest.queue_rows").snapshot() <= 32


def test_app_garbage_chaos_skips_and_counts(tmp_path):
    """source.garbage on block ingest: corrupted buffers are skipped and
    counted (ingest.rows_dropped_parse), never a crash — and the rows from
    undamaged buffers still train."""
    import jax

    from twtml_tpu.apps import linear_regression as app

    jax.devices()
    path = tmp_path / "tweets.jsonl"
    _write_replay(path, 64, seed=43)
    totals = app.run(ConfArguments().parse([
        "--source", "replay", "--replayFile", str(path),
        "--ingest", "block", "--seconds", "0", "--backend", "cpu",
        "--batchBucket", "16", "--tokenBucket", "64",
        "--master", "local[1]",
        # a small file parses as ONE chunk, so damage every parse call
        "--chaos", "source.garbage@1",
        "--lightning", CLOSED, "--twtweb", CLOSED, "--webTimeout", "0.2",
    ]))
    reg = _metrics.get_registry()
    assert reg.counter("chaos.source.garbage.injected").snapshot() > 0
    # damage was absorbed: rows were lost (truncation + garbled lines,
    # counted where they died as parse lines), not the process
    assert 0 < totals["count"] < 64
    assert reg.counter("ingest.rows_dropped_parse").snapshot() > 0
