"""Launcher for multi-host APP-LEVEL integration tests: configures a CPU/gloo
jax runtime, then drives a REAL entry-point main() with its own CLI — the
reference's one-flag cluster story exercised end to end
(``--coordinator host:port --numProcesses N --processId I``,
apps/common.init_distributed).

Not a test module — spawned by tests/test_distributed_multiprocess.py.

Usage: python tests/app_worker.py <process_id> <num_processes> <port> \
           <devices_per_process> <app> [app args...]

``num_processes == 1`` runs the same main single-host (no coordinator
flags) — the ground-truth run the multi-host stats must match.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

pid, nprocs, port, ndev = (
    int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4])
)
app_name, app_args = sys.argv[5], list(sys.argv[6:])

jax.config.update("jax_platforms", "cpu")
if nprocs > 1:
    # gloo needs the distributed client on older jax (0.4.x requires it at
    # backend init): only request it when this worker actually joins a group
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
from twtml_tpu.utils.backend import set_cpu_device_count_hint  # noqa: E402

set_cpu_device_count_hint(ndev)  # jax_num_cpu_devices or XLA_FLAGS fallback

if nprocs > 1:
    app_args += [
        "--master", f"twtml://127.0.0.1:{port}",  # the cluster master URL
        "--numProcesses", str(nprocs),
        "--processId", str(pid),
    ]

from twtml_tpu.apps import (  # noqa: E402
    kmeans,
    linear_regression,
    logistic_regression,
)

{
    "linear": linear_regression,
    "logistic": logistic_regression,
    "kmeans": kmeans,
}[app_name].main(app_args)
