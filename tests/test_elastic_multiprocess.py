"""Elastic lockstep membership over REAL multi-process gloo groups (r16,
ISSUE 13; lead election r20, ISSUE 17): the fleet that shrinks,
rebalances, rejoins — and now survives its own coordinator.

Acceptance (ISSUE 13):
- ``--chaos peer.kill`` on host 1 → host 0 SHRINKS to a 1-host group
  within the watchdog window and keeps training — no abort, departed rows
  counted, and the survivor's continuation is bit-equal to a clean run
  from the restored checkpoint;
- a restarted host is ADMITTED at an epoch boundary and its first-tick
  weights bit-match the lead's (matching state CRCs on every host);
- zero new collectives per healthy tick with the membership plane ACTIVE
  (``process_allgather`` counted over a real lockstep run, the PR 1/5
  idiom) and zero added host fetches;
- the cross-host compressed-wire bucket (``--wireCodec dict`` on
  multi-host, ROADMAP item 3 REMAINING) trains stats-identically to the
  raw multi-host wire — the agreement rides the existing alignment
  allgather.

Acceptance (ISSUE 17 — kill the LEAD, the last single point of failure):
- ``--chaos peer.kill:uid=0`` kills the lead mid-run → the survivor
  detects the orphaned beacon, WINS the election (deterministic successor
  rule: lowest live uid of the committed view), re-binds the beacon,
  promotes its shadow checkpoint lineage, and keeps training — with a
  continuation BIT-equal to a clean run from its own verified archives;
- the healthy-tick zero-new-collectives law holds at 8-host scale
  (the allgather count IS the tick count with 8 members' columns riding
  it), and an 8-host churn storm (follower kill + lead kill + pauses,
  driven by tools/chaos_fleet.py) forms every epoch with fleet-wide
  CRC-identical resyncs and counted losses.
"""

from __future__ import annotations

import json
import os
import re
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "distributed_worker.py")
APP_WORKER = os.path.join(REPO, "tests", "app_worker.py")

NOW_MS = 1785320000000
CLOSED = "http://127.0.0.1:9"  # closed port: telemetry Try paths, no DNS


def _free_port_range(span: int = 10) -> int:
    """A base port with ``span`` consecutive free ports: elastic reserves
    base (epoch-0 compat), base+1 (beacon), base+2+e (epoch e)."""
    for cand in range(29500, 61000, span + 3):
        socks, ok = [], True
        for off in range(span):
            s = socket.socket()
            try:
                s.bind(("127.0.0.1", cand + off))
                socks.append(s)
            except OSError:
                ok = False
                break
        for s in socks:
            s.close()
        if ok:
            return cand
    raise RuntimeError("no contiguous free port range found")


def _write_replay(tmp_path, total: int, seed: int = 5):
    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    path = tmp_path / "tweets.jsonl"
    statuses = list(
        SyntheticSource(total=total, seed=seed, base_ms=NOW_MS).produce()
    )
    with open(path, "w") as fh:
        for s in statuses:
            fh.write(json.dumps(_status_json(s)) + "\n")
    return path, statuses


def _elastic_args(path, ck, extra=()):
    return [
        "linear", "--source", "replay", "--replayFile", str(path),
        "--seconds", "0", "--backend", "cpu",
        "--batchBucket", "16", "--tokenBucket", "64",
        "--checkpointDir", str(ck), "--elastic", "on",
        "--lightning", CLOSED, "--twtweb", CLOSED,
    ] + list(extra)


def _spawn_app(pid, nprocs, base, args, env):
    return subprocess.Popen(
        [sys.executable, APP_WORKER, str(pid), str(nprocs), str(base), "2"]
        + args,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
    )


def _elastic_env(**extra):
    env = dict(
        os.environ, PYTHONPATH=REPO, TWTML_NOW_MS=str(NOW_MS),
        TWTML_LOCKSTEP_TIMEOUT_S="5", TWTML_ELASTIC_RESCUE_GRACE_S="2",
    )
    env.update(extra)
    return env


def _stat_lines(out: str):
    return [ln for ln in out.splitlines() if ln.startswith("count:")]


def test_healthy_elastic_tick_adds_no_collectives_and_no_fetches():
    """The PR 1/5 law with the membership plane ACTIVE: membership columns
    widen the cadence allgather's payload, never its call count, and the
    pooled stats fetch stays one device_get per dispatched batch."""
    base = _free_port_range()
    env = dict(os.environ, PYTHONPATH=REPO)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), "2", str(base), "unit",
             "elastic_count"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=240.0)
            if p.returncode != 0:
                pytest.fail(
                    f"worker failed rc={p.returncode}:\n{stderr[-3000:]}"
                )
            outs.append(json.loads(stdout.strip().splitlines()[-1]))
    finally:
        for p in procs:
            p.kill()
    for o in outs:
        assert o["terminated"] and not o["failed"]
        assert o["batches"] >= 6
        # ZERO new collectives: the allgather count IS the tick count,
        # membership columns included
        assert o["allgathers"] == o["ticks"], o
        # ZERO added host fetches: one pooled get per dispatched batch
        assert o["device_gets"] == o["batches"] == o["fetch_count"], o
        # a healthy run never transitions
        assert o["epoch"] == 0 and o["members"] == [0, 1]
        assert o["transitions"] == []


def test_peer_kill_shrinks_and_survivor_bitmatches_clean_run(tmp_path):
    """THE shrink acceptance: host 1 hard-dies at lockstep tick 4 (no
    abort broadcast — ``--chaos peer.kill``); host 0 must shrink to a
    1-host epoch within the watchdog window and keep training. No abort,
    departed rows counted, and the survivor's post-shrink trajectory is
    BIT-EQUAL to a clean run started from the restored checkpoint over
    the surviving intake."""
    import shutil
    import threading

    path, statuses = _write_replay(tmp_path, 200)
    ck = tmp_path / "ck"
    ck.mkdir()
    keep = tmp_path / "archives"  # rotation-proof copies of every save
    keep.mkdir()
    stop_copier = threading.Event()

    def copier():
        seen = set()
        while not stop_copier.is_set():
            for f in ck.glob("ckpt-*.npz"):
                if f.name not in seen:
                    try:
                        shutil.copy2(f, keep / f.name)
                        seen.add(f.name)
                    except OSError:
                        pass  # racing the writer's rename; next pass wins
            stop_copier.wait(0.05)

    copier_thread = threading.Thread(target=copier, daemon=True)
    copier_thread.start()

    base = _free_port_range()
    env = _elastic_env()
    args = _elastic_args(path, ck, extra=["--checkpointEvery", "1"])
    lead = _spawn_app(0, 2, base, args, env)
    peer = _spawn_app(1, 2, base, args + ["--chaos", "peer.kill:tick=4"], env)
    try:
        lo, le = lead.communicate(timeout=420.0)
        po, pe = peer.communicate(timeout=60.0)
    finally:
        stop_copier.set()
        copier_thread.join(timeout=5)
    assert peer.returncode == 77, f"peer did not chaos-exit:\n{pe[-2000:]}"
    assert lead.returncode == 0, f"survivor failed:\n{le[-4000:]}"

    # no abort: the survivor SHRANK and completed
    assert "aborting" not in le or "instead of aborting" in le
    assert "elastic epoch 1 formed: 1 host(s) [0]" in le
    assert "intake shard rebalanced: now serving residues [0, 1] of 2" in le
    assert "rows_lost_estimate" in le  # departed rows counted, never silent
    lines = _stat_lines(lo)
    assert lines, "survivor printed no stats"
    # pre-kill global batches are 32 rows (two 16-row host shards); the
    # shrunken epoch's are host 0's 16-row buckets
    assert "count: 96  batch: 32" in lines[2]
    # the run covered everything except the dead host's lost share:
    # host 0 trained its full 100-row shard (statuses[0::2])
    final_count = int(re.findall(r"count: (\d+)", lines[-1])[0])
    assert final_count == 148  # 96 global + host 0's remaining 52

    # ---- bit-equality vs a clean run from the restored checkpoint ------
    # The rescue restored checkpoint step 3 (count=96); the survivor then
    # trained host 0's rows 48.. in 16-row buckets on a 2-device mesh.
    # Rebuild exactly that, in process, from the SAME archive.
    import jax

    from twtml_tpu.checkpoint import Checkpointer
    from twtml_tpu.config import ConfArguments
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.parallel import ParallelSGDModel, make_mesh

    resync = re.search(
        r"elastic resync: state from the lead's verified checkpoint "
        r"\(count=(\d+), batches=(\d+), state crc ([0-9a-f]+)\)", le,
    )
    assert resync is not None, "survivor never logged the resync"
    assert int(resync.group(1)) == 96 and int(resync.group(2)) == 3

    from twtml_tpu.apps.common import state_checksum

    ckpt = Checkpointer(str(ck))
    state3, meta3 = Checkpointer(str(keep)).restore(step=3)
    # the restored state the survivor continued from is BIT-equal to the
    # verified step-3 archive: the logged resync CRC is its checksum
    assert resync.group(3) == state_checksum(state3)
    conf = ConfArguments().parse(["--backend", "cpu"])
    mesh = make_mesh(num_data=2, devices=jax.devices()[:2])
    model = ParallelSGDModel.from_conf(conf, mesh).set_initial_weights(state3)
    feat = Featurizer(now_ms=NOW_MS)
    shard0 = statuses[0::2]
    for lo_i in range(48, len(shard0), 16):
        batch = feat.featurize_batch_ragged(
            shard0[lo_i:lo_i + 16], row_bucket=16, unit_bucket=64,
            row_multiple=2,
        )
        model.step(model.pack_for_wire(batch))
    final_state, meta = ckpt.restore()
    assert meta["count"] == 148
    np.testing.assert_array_equal(
        np.asarray(final_state), np.asarray(model.latest_weights),
        err_msg="survivor state is not bit-equal to the clean "
                "run-from-checkpoint",
    )


def test_shrink_replays_rolled_back_rows_from_journal(tmp_path):
    """THE replay-after-shrink acceptance (ISSUE 19, two-process gloo):
    with ``--checkpointEvery 2`` the newest verified archive at the kill
    is batch 2, so the rescue THROWS BATCH 3 AWAY — discarded in-flight
    (its collectives died with the peer) or rolled back by the resync —
    where the pre-journal behavior counted those rows lost. With the
    intake journal on (auto via ``--checkpointDir``), the survivor
    re-ingests its own 16 thrown-away rows from its journal (replayed ==
    discarded+rolled, exactly), and the continuation is BIT-EQUAL to a
    clean run from the step-2 archive over the survivor's rows 32.. —
    zero rows lost to the rescue."""
    import shutil
    import threading

    path, statuses = _write_replay(tmp_path, 200)
    ck = tmp_path / "ck"
    ck.mkdir()
    keep = tmp_path / "archives"  # rotation-proof copies of every save
    keep.mkdir()
    stop_copier = threading.Event()

    def copier():
        seen = set()
        while not stop_copier.is_set():
            for f in ck.glob("ckpt-*.npz"):
                if f.name not in seen:
                    try:
                        shutil.copy2(f, keep / f.name)
                        seen.add(f.name)
                    except OSError:
                        pass  # racing the writer's rename; next pass wins
            stop_copier.wait(0.05)

    copier_thread = threading.Thread(target=copier, daemon=True)
    copier_thread.start()

    base = _free_port_range()
    env = _elastic_env()
    args = _elastic_args(path, ck, extra=["--checkpointEvery", "2"])
    lead = _spawn_app(0, 2, base, args, env)
    peer = _spawn_app(1, 2, base, args + ["--chaos", "peer.kill:tick=4"], env)
    try:
        lo, le = lead.communicate(timeout=420.0)
        po, pe = peer.communicate(timeout=60.0)
    finally:
        stop_copier.set()
        copier_thread.join(timeout=5)
    assert peer.returncode == 77, f"peer did not chaos-exit:\n{pe[-2000:]}"
    assert lead.returncode == 0, f"survivor failed:\n{le[-4000:]}"
    assert "elastic epoch 1 formed: 1 host(s) [0]" in le

    # the rescue threw batch 3 away — past the step-2 archive, it is
    # either a discarded in-flight output (dispatched, never delivered:
    # the dead peer poisoned its collectives) or delivered post-checkpoint
    # progress the resync rolled back; both forms are counted, and the
    # survivor's share is its 16-row batch either way
    resync = re.search(
        r"elastic resync: state from the lead's verified checkpoint "
        r"\(count=(\d+), batches=(\d+), state crc ([0-9a-f]+)\)"
        r"(?: — (\d+) row\(s\) of post-checkpoint progress rolled back)?",
        le,
    )
    assert resync is not None, "survivor never logged the resync"
    assert int(resync.group(1)) == 64 and int(resync.group(2)) == 2
    rolled_share = int(resync.group(4) or 0) // 2  # global rows, 2 hosts
    discarded = sum(
        int(r) for r in re.findall(
            r"elastic rescue: discarded \d+ in-flight.*?\(~(\d+) "
            r"row\(s\)\)", le,
        )
    )
    assert rolled_share + discarded == 16, (rolled_share, discarded)

    # the journal converted the survivor's share into a replay: replayed
    # rows == this host's thrown-away rows, exactly
    replay = re.search(
        r"journal: replayed (\d+) row\(s\) from cursor (\d+) after "
        r"elastic rescue — counters reset to \(count=64, batches=2\); "
        r"recovery is replay-exact, zero rows lost", le,
    )
    assert replay is not None, f"survivor never replayed:\n{le[-4000:]}"
    assert int(replay.group(1)) == rolled_share + discarded == 16
    assert int(replay.group(2)) == 2  # the step-2 archive's cursor stamp

    # ledger: 64 restored + the survivor's rows 32.. of its 100-row shard
    # (the replayed 16, the interrupted tick's 16, then the source tail);
    # only the DEAD host's rolled-back+remaining rows are lost with it
    lines = _stat_lines(lo)
    assert lines, "survivor printed no stats"
    final_count = int(re.findall(r"count: (\d+)", lines[-1])[0])
    assert final_count == 132  # 64 global + host 0's remaining 68

    # ---- bit-equality vs a clean run from the step-2 archive -----------
    import jax

    from twtml_tpu.apps.common import state_checksum
    from twtml_tpu.checkpoint import Checkpointer
    from twtml_tpu.config import ConfArguments
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.parallel import ParallelSGDModel, make_mesh

    state2, meta2 = Checkpointer(str(keep)).restore(step=2)
    assert resync.group(3) == state_checksum(state2)
    assert meta2["journal"] == {"cursor": 2, "rows": 32}
    conf = ConfArguments().parse(["--backend", "cpu"])
    mesh = make_mesh(num_data=2, devices=jax.devices()[:2])
    model = ParallelSGDModel.from_conf(conf, mesh).set_initial_weights(state2)
    feat = Featurizer(now_ms=NOW_MS)
    shard0 = statuses[0::2]
    for lo_i in range(32, len(shard0), 16):
        batch = feat.featurize_batch_ragged(
            shard0[lo_i:lo_i + 16], row_bucket=16, unit_bucket=64,
            row_multiple=2,
        )
        model.step(model.pack_for_wire(batch))
    final_state, meta = Checkpointer(str(ck)).restore()
    assert meta["count"] == 132
    np.testing.assert_array_equal(
        np.asarray(final_state), np.asarray(model.latest_weights),
        err_msg="replayed continuation is not bit-equal to the clean "
                "run-from-step-2-archive",
    )


def test_lead_kill_elects_successor_and_bitmatches_clean_run(tmp_path):
    """THE election acceptance (ISSUE 17): the LEAD hard-dies at lockstep
    tick 4 (``--chaos peer.kill:uid=0`` — one fleet-wide spec, the uid
    selector picks the victim). The survivor's wedge report hits an
    ORPHANED beacon (connection refused — a dead lead, not a paused one),
    so it elects: sole candidate, rank 0, re-binds the beacon, promotes
    its standby checkpoint lineage, restores its OWN verified step-3
    archive, and finishes the run as the new lead. No abort, the dead
    lead's departed rows counted, and the survivor's post-election
    trajectory is BIT-EQUAL to a clean run from the promoted archive."""
    import shutil
    import threading

    path, statuses = _write_replay(tmp_path, 200)
    ck = tmp_path / "ck"
    ck.mkdir()
    standby = ck / "standby-u1"  # uid 1's shadow-save lineage
    keep = tmp_path / "archives"  # rotation-proof copies of every save
    keep.mkdir()
    stop_copier = threading.Event()

    def copier():
        seen = set()
        while not stop_copier.is_set():
            for f in standby.glob("ckpt-*.npz"):
                if f.name not in seen:
                    try:
                        shutil.copy2(f, keep / f.name)
                        seen.add(f.name)
                    except OSError:
                        pass  # racing the writer's rename; next pass wins
            stop_copier.wait(0.05)

    copier_thread = threading.Thread(target=copier, daemon=True)
    copier_thread.start()

    base = _free_port_range()
    env = _elastic_env()
    # the SAME command line on every host: the uid selector does the aiming
    args = _elastic_args(path, ck, extra=[
        "--checkpointEvery", "1", "--chaos", "peer.kill:uid=0:tick=4",
    ])
    lead = _spawn_app(0, 2, base, args, env)
    surv = _spawn_app(1, 2, base, args, env)
    try:
        so, se = surv.communicate(timeout=420.0)
        lo, le = lead.communicate(timeout=60.0)
    finally:
        stop_copier.set()
        copier_thread.join(timeout=5)
    assert lead.returncode == 77, f"lead did not chaos-exit:\n{le[-2000:]}"
    assert surv.returncode == 0, f"survivor failed:\n{se[-4000:]}"

    # the survivor ELECTED itself instead of aborting: orphaned beacon
    # detected, bind won, authority promoted, epoch formed without uid 0
    assert "the lead (uid 0) is gone; electing a successor" in se
    assert "uid 1 WON the election (beacon :" in se
    assert "checkpoint authority PROMOTED after lead election" in se
    assert "elastic epoch 1 formed: 1 host(s) [1]" in se
    assert "intake shard rebalanced: now serving residues [0, 1] of 2" in se
    assert "rows_lost_estimate" in se  # the dead lead's share, never silent
    # telemetry ownership stayed with launch-time process 0 (now dead):
    # the survivor's proof lives in its logs and its promoted archives
    assert _stat_lines(so) == []

    # ---- bit-equality vs a clean run from the PROMOTED archive ---------
    # The election restored uid 1's standby step-3 checkpoint (count=96);
    # the survivor then trained host 1's rows 48.. in 16-row buckets.
    import jax

    from twtml_tpu.checkpoint import Checkpointer
    from twtml_tpu.config import ConfArguments
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.parallel import ParallelSGDModel, make_mesh

    resync = re.search(
        r"elastic resync: state from the lead's verified checkpoint "
        r"\(count=(\d+), batches=(\d+), state crc ([0-9a-f]+)\)", se,
    )
    assert resync is not None, "survivor never logged the resync"
    assert int(resync.group(1)) == 96 and int(resync.group(2)) == 3

    from twtml_tpu.apps.common import state_checksum

    state3, meta3 = Checkpointer(str(keep)).restore(step=3)
    # the state the new lead continued from is BIT-equal to its own
    # verified step-3 shadow archive: the logged resync CRC is its checksum
    assert resync.group(3) == state_checksum(state3)
    conf = ConfArguments().parse(["--backend", "cpu"])
    mesh = make_mesh(num_data=2, devices=jax.devices()[:2])
    model = ParallelSGDModel.from_conf(conf, mesh).set_initial_weights(state3)
    feat = Featurizer(now_ms=NOW_MS)
    shard1 = statuses[1::2]
    for lo_i in range(48, len(shard1), 16):
        batch = feat.featurize_batch_ragged(
            shard1[lo_i:lo_i + 16], row_bucket=16, unit_bucket=64,
            row_multiple=2,
        )
        model.step(model.pack_for_wire(batch))
    # post-promotion saves continued into the standby directory — it IS
    # the fleet lineage now
    final_state, meta = Checkpointer(str(standby)).restore()
    assert meta["count"] == 148  # 96 global + host 1's remaining 52
    np.testing.assert_array_equal(
        np.asarray(final_state), np.asarray(model.latest_weights),
        err_msg="elected lead's state is not bit-equal to the clean "
                "run-from-promoted-checkpoint",
    )


def test_killed_host_rejoins_with_bitmatching_weights(tmp_path):
    """THE rejoin acceptance: after the shrink, the SAME command line
    restarted parks at the lead's beacon, is admitted at the next epoch
    boundary, and restores the broadcast checkpoint BEFORE its first tick
    — its state CRC matches the lead's resync CRC exactly."""
    path, _statuses = _write_replay(tmp_path, 1600)
    ck = tmp_path / "ck"
    base = _free_port_range()
    env = _elastic_env()
    args = _elastic_args(path, ck, extra=["--checkpointEvery", "4"])
    lead = _spawn_app(0, 2, base, args, env)
    peer = _spawn_app(1, 2, base, args + ["--chaos", "peer.kill:tick=4"], env)
    po, pe = peer.communicate(timeout=120.0)
    assert peer.returncode == 77
    time.sleep(6.0)  # let the rescue land; the lead trains on alone
    rejoiner = _spawn_app(1, 2, base, args, env)
    lo, le = lead.communicate(timeout=600.0)
    ro, re_ = rejoiner.communicate(timeout=300.0)
    assert lead.returncode == 0, f"lead failed:\n{le[-4000:]}"
    assert rejoiner.returncode == 0, f"rejoiner failed:\n{re_[-4000:]}"

    assert "parking this host (uid 1) for admission" in re_
    assert "proposing epoch 2 with members [0, 1] (join)" in le
    assert "elastic epoch 2 formed: 2 host(s) [0, 1]" in le
    assert "joined a live replay-sharded run as a hot standby" in re_

    # first-tick weights bit-match: the lead's admission-boundary resync
    # CRC equals the rejoiner's post-broadcast sync CRC
    lead_crcs = re.findall(r"elastic resync: .* state crc ([0-9a-f]+)", le)
    join_crcs = re.findall(
        r"multi-host state synchronized from the lead \(count=\d+, "
        r"state crc ([0-9a-f]+)\)", re_,
    )
    assert lead_crcs and join_crcs
    assert join_crcs[-1] == lead_crcs[-1], (
        "rejoiner's first-tick state does not bit-match the lead's"
    )
    # one telemetry owner throughout; the lead finished the whole file
    assert _stat_lines(ro) == []
    assert _stat_lines(lo)


def test_wirecodec_dict_multihost_matches_raw_wire(tmp_path):
    """ROADMAP item 3 REMAINING: the cross-host compressed bucket rides
    the existing alignment allgather, and a two-process ``--wireCodec
    dict`` run trains IDENTICALLY (published stats byte-for-byte, final
    weights bitwise) to the raw-wire two-process run — compression is
    representation-only at fleet scale too."""
    path, _statuses = _write_replay(tmp_path, 160, seed=9)
    env = dict(os.environ, PYTHONPATH=REPO, TWTML_NOW_MS=str(NOW_MS))

    def run(codec: str, ck):
        base = _free_port_range()
        common = [
            "linear", "--source", "replay", "--replayFile", str(path),
            "--seconds", "0", "--backend", "cpu",
            "--batchBucket", "16", "--tokenBucket", "64",
            "--wire", "ragged", "--hashOn", "device",
            "--wireCodec", codec, "--checkpointDir", str(ck),
            "--lightning", CLOSED, "--twtweb", CLOSED,
        ]
        procs = [_spawn_app(i, 2, base, common, env) for i in range(2)]
        outs, errs = [], []
        for p in procs:
            o, e = p.communicate(timeout=420.0)
            if p.returncode != 0:
                pytest.fail(f"worker rc={p.returncode}:\n{e[-3000:]}")
            outs.append(o)
            errs.append(e)
        return outs, errs

    raw, _raw_errs = run("off", tmp_path / "ck_raw")
    codec, codec_errs = run("dict", tmp_path / "ck_dict")
    # the codec arm must actually COMPRESS (synthetic tweets are ASCII):
    # a silent raw fallback would make this differential vacuous
    for e in codec_errs:
        assert "shipped RAW" not in e, e[-2000:]
    assert _stat_lines(raw[1]) == _stat_lines(codec[1]) == []
    assert _stat_lines(raw[0]) == _stat_lines(codec[0])
    assert len(_stat_lines(raw[0])) >= 4

    from twtml_tpu.checkpoint import Checkpointer

    w_raw, m_raw = Checkpointer(str(tmp_path / "ck_raw")).restore()
    w_dict, m_dict = Checkpointer(str(tmp_path / "ck_dict")).restore()
    assert m_raw["count"] == m_dict["count"] == 160
    np.testing.assert_array_equal(np.asarray(w_raw), np.asarray(w_dict))


def test_tenant_fleet_two_process_matches_single_process(tmp_path):
    """PR 7 REMAINING b: ``--tenants M`` + ``--coordinator`` now runs —
    per-host sharded intake into the stacked tenant wire, ONE pooled
    fetch per tick — and the two-process fleet's published stats and
    final stacked weights match a single-process tenant run of the same
    app over the same replay."""
    path, _statuses = _write_replay(tmp_path, 128, seed=11)
    env = dict(os.environ, PYTHONPATH=REPO, TWTML_NOW_MS=str(NOW_MS))
    common = [
        "linear", "--source", "replay", "--replayFile", str(path),
        "--seconds", "0", "--backend", "cpu", "--tenants", "2",
        "--wire", "padded", "--tokenBucket", "64",
        "--lightning", CLOSED, "--twtweb", CLOSED,
    ]

    def run(nprocs, ndev, bucket, ck):
        base = _free_port_range()
        args = common + ["--batchBucket", bucket, "--checkpointDir", str(ck)]
        procs = [
            subprocess.Popen(
                [sys.executable, APP_WORKER, str(i), str(nprocs), str(base),
                 str(ndev)] + args,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                env=env,
            )
            for i in range(nprocs)
        ]
        outs = []
        for p in procs:
            o, e = p.communicate(timeout=420.0)
            if p.returncode != 0:
                pytest.fail(f"worker rc={p.returncode}:\n{e[-3000:]}")
            outs.append(o)
        return outs

    single = run(1, 4, "32", tmp_path / "ck1")
    multi = run(2, 2, "16", tmp_path / "ck2")
    lead, follower = _stat_lines(multi[0]), _stat_lines(multi[1])
    ref = _stat_lines(single[0])
    assert follower == []
    assert len(lead) == len(ref) >= 3
    for got, want in zip(lead, ref):
        g = [int(x) for x in re.findall(r"-?\d+", got)]
        w = [int(x) for x in re.findall(r"-?\d+", want)]
        assert g[:2] == w[:2]  # cumulative count and batch size: exact
        for a, b in zip(g[2:], w[2:]):
            assert abs(a - b) <= 2, (got, want)

    from twtml_tpu.checkpoint import Checkpointer

    w_single, m_s = Checkpointer(str(tmp_path / "ck1")).restore()
    w_multi, m_m = Checkpointer(str(tmp_path / "ck2")).restore()
    assert m_s["count"] == m_m["count"] == 128
    assert np.asarray(w_single).shape == np.asarray(w_multi).shape  # [M, F+4]
    np.testing.assert_allclose(
        np.asarray(w_multi), np.asarray(w_single), rtol=1e-4, atol=1e-7,
    )


@pytest.mark.slow
def test_healthy_eight_host_fleet_adds_no_collectives_and_no_fetches():
    """The zero-new-collectives law AT SCALE (ISSUE 17): an 8-process
    lockstep fleet with the membership plane active — 8 hosts' membership
    columns widen the one cadence allgather's payload, never its call
    count, and the pooled stats fetch stays one device_get per batch."""
    nprocs = 8
    base = _free_port_range()
    env = dict(os.environ, PYTHONPATH=REPO)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), str(nprocs), str(base), "unit",
             "elastic_count"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for i in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=420.0)
            if p.returncode != 0:
                pytest.fail(
                    f"worker failed rc={p.returncode}:\n{stderr[-3000:]}"
                )
            outs.append(json.loads(stdout.strip().splitlines()[-1]))
    finally:
        for p in procs:
            p.kill()
    for o in outs:
        assert o["terminated"] and not o["failed"]
        assert o["batches"] >= 2  # 192 rows / 8 hosts = 24 each, bucket 16
        assert o["allgathers"] == o["ticks"], o
        assert o["device_gets"] == o["batches"] == o["fetch_count"], o
        assert o["epoch"] == 0 and o["members"] == list(range(nprocs))
        assert o["transitions"] == []


@pytest.mark.slow
def test_churn_storm_eight_hosts_survives_follower_and_lead_kills(tmp_path):
    """THE churn acceptance (ISSUE 17): an 8-host virtual fleet under the
    storm driver (tools/chaos_fleet.py) — a follower dies, the fleet
    shrinks; the LEAD dies, uid 1 wins the election and re-forms; a pause
    stalls a third host under the watchdog threshold (no transition). All
    epochs form, every survivor's per-reform resync CRC matches fleet-wide
    (bit-matching continuations), losses are counted, and no host aborts."""
    from tools.chaos_fleet import run_storm

    res = run_storm(
        hosts=8, tweets=1024, workdir=str(tmp_path),
        chaos=(
            "peer.kill:uid=5:tick=2,peer.kill:uid=0:tick=6,"
            "peer.pause:uid=3:ticks=1@4"
        ),
    )
    assert res["ok"], res["failures"]
    assert sorted(res["killed"]) == [0, 5]
    # one election, won by the lowest live uid of the committed view
    assert res["elections"] == 1
    assert res["winners"] == [1]
    # the fleet walked the full epoch ladder: the initial 8, then 7
    # (uid 5 dead), then 7 without uid 0 but with the elected lead (uid 1)
    assert [m for _e, m in res["epochs"]] == [
        list(range(8)), [0, 1, 2, 3, 4, 6, 7], [1, 2, 3, 4, 6, 7],
    ]
    # every reform's resync CRC agreed across every member that logged it
    assert res["crc_rounds"] and all(
        len(set(crcs)) == 1 for crcs in res["crc_rounds"]
    )
    # the sub-threshold pause caused churn, not a transition
    assert res["pauses"] >= 1 and len(res["epochs"]) == 3
