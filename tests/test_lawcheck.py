"""Law-checker (tools/lawcheck): the measured laws, enforced statically.

Every rule must FIRE on a seeded violation and stay quiet on the blessed
pattern right next to it — a checker that can't catch the violation it was
built for is worse than none (it certifies). Plus the machinery contracts:
suppressions need reasons, the baseline grandfathers by fingerprint, the
--json/exit-code surface is what CI gates on, and — the acceptance
criterion — THIS repo is clean with an EMPTY baseline.
"""

from __future__ import annotations

import json

import pytest

from tools.lawcheck import engine
from tools.lawcheck.rules import all_rules, rule_ids

# a minimal config.py whose parse() registers --foo (documented) — keeps
# TW007 satisfied in mini-repos that aren't exercising it
_MINI_CONFIG = '''
class ConfArguments:
    def parse(self, args):
        flag = args[0]
        if flag == "--foo":
            pass
        return self
'''
_MINI_README = "Use `--foo` to foo.\n"


def mini_repo(tmp_path, files: dict[str, str]):
    """Materialize a fake checkout: default config/docs plus ``files``."""
    defaults = {
        "twtml_tpu/config.py": _MINI_CONFIG,
        "README.md": _MINI_README,
        "SCALING.md": "nothing here\n",
    }
    defaults.update(files)
    for rel, content in defaults.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(content, encoding="utf-8")
    return tmp_path


def run(tmp_path, files: dict[str, str]):
    root = mini_repo(tmp_path, files)
    return engine.run_repo(root=str(root),
                           baseline_path=str(root / "baseline.json"))


def rules_fired(report):
    return sorted({f.rule for f in report.findings})


# ---------------------------------------------------------------------------
# per-rule seeded violations


def test_tw001_fires_on_module_scope_backend_init(tmp_path):
    report = run(tmp_path, {"twtml_tpu/foo.py": (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "DEVICES = jax.devices()\n"
        "ZEROS = jnp.zeros((8,))\n"
        "def fine():\n"
        "    return jax.devices()\n"
    )})
    lines = [f.line for f in report.findings if f.rule == "TW001"]
    assert lines == [3, 4]  # the function body is NOT import-time


def test_tw001_class_body_counts_as_import_time(tmp_path):
    report = run(tmp_path, {"twtml_tpu/foo.py": (
        "import jax.numpy as jnp\n"
        "class C:\n"
        "    TABLE = jnp.arange(4)\n"
    )})
    assert rules_fired(report) == ["TW001"]


def test_tw001_allowlists_conftest_and_backend_helper(tmp_path):
    report = run(tmp_path, {
        "tests/conftest.py": "import jax\nD = jax.devices()\n",
        "twtml_tpu/utils/backend.py": "import jax\nD = jax.devices()\n",
    })
    assert report.findings == []


def test_tw002_fires_outside_seams_quiet_inside(tmp_path):
    bad = (
        "import jax\n"
        "def f(out):\n"
        "    host = jax.device_get(out)\n"
        "    out.block_until_ready()\n"
        "    return host\n"
    )
    report = run(tmp_path, {
        "twtml_tpu/streaming/thing.py": bad,
        "twtml_tpu/apps/common.py": bad,    # the seam implementation
        "twtml_tpu/utils/benchloop.py": bad,  # the other seam
        "tools/bench_x.py": bad,            # tools are out of scope
        "tests/test_x.py": bad,             # tests count fetches themselves
    })
    assert [(f.path, f.line) for f in report.findings] == [
        ("twtml_tpu/streaming/thing.py", 3),
        ("twtml_tpu/streaming/thing.py", 4),
    ]


def test_tw003_fires_on_thread_target_reaching_device_put(tmp_path):
    report = run(tmp_path, {"twtml_tpu/parallel/up.py": (
        "import threading\n"
        "import jax\n"
        "def uploader(x):\n"
        "    return jax.device_put(x)\n"
        "def spawn():\n"
        "    threading.Thread(target=uploader).start()\n"
    )})
    assert [(f.rule, f.line) for f in report.findings] == [("TW003", 6)]


def test_tw003_one_level_deep_and_submit(tmp_path):
    report = run(tmp_path, {"twtml_tpu/parallel/up.py": (
        "import jax\n"
        "def put_helper(x):\n"
        "    return jax.device_put(x)\n"
        "def worker(x):\n"
        "    return put_helper(x)\n"
        "class P:\n"
        "    def go(self, pool, x):\n"
        "        pool.submit(worker, x)\n"
    )})
    assert [(f.rule, f.line) for f in report.findings] == [("TW003", 8)]


def test_tw003_quiet_on_fetch_side_threads(tmp_path):
    report = run(tmp_path, {"twtml_tpu/parallel/down.py": (
        "import jax\n"
        "def fetcher(x):\n"
        "    return jax.device_get(x)\n"
        "def go(pool, out):\n"
        "    pool.submit(fetcher, out)\n"
        "    pool.submit(jax.device_get, out)\n"
    )})
    assert [f for f in report.findings if f.rule == "TW003"] == []


def test_tw004_fires_in_step_code_only(tmp_path):
    scatter = (
        "import jax.numpy as jnp\n"
        "def grad(w, idx, v):\n"
        "    return w.at[idx].add(v)\n"
    )
    report = run(tmp_path, {
        "twtml_tpu/ops/newop.py": scatter,
        "twtml_tpu/models/newmodel.py": scatter,
        "twtml_tpu/streaming/hostside.py": scatter,  # not step code
    })
    assert [(f.path, f.rule) for f in report.findings] == [
        ("twtml_tpu/models/newmodel.py", "TW004"),
        ("twtml_tpu/ops/newop.py", "TW004"),
    ]


def test_tw005_fires_on_silent_swallow_quiet_on_handled(tmp_path):
    report = run(tmp_path, {"twtml_tpu/streaming/sw.py": (
        "import logging\n"
        "def a():\n"
        "    try:\n"
        "        work()\n"
        "    except Exception:\n"
        "        pass\n"
        "def b():\n"
        "    try:\n"
        "        work()\n"
        "    except Exception:\n"
        "        logging.exception('batch failed')\n"
        "def c():\n"
        "    try:\n"
        "        work()\n"
        "    except ValueError:\n"
        "        pass\n"
        "def d():\n"
        "    try:\n"
        "        work()\n"
        "    except BaseException:\n"
        "        raise\n"
    )})
    assert [(f.rule, f.line) for f in report.findings] == [("TW005", 5)]


def test_tw005_try_parity_files_are_exempt(tmp_path):
    swallow = "try:\n    x = 1\nexcept Exception:\n    pass\n"
    report = run(tmp_path, {
        "twtml_tpu/telemetry/session_stats.py": swallow,
        "twtml_tpu/telemetry/web_client.py": swallow,
    })
    assert report.findings == []


def test_tw006_fires_on_wall_clock_in_replay_scope(tmp_path):
    report = run(tmp_path, {"twtml_tpu/serving/sched.py": (
        "import time\n"
        "def tick():\n"
        "    t = time.time()\n"
        "    d = time.monotonic()\n"
        "    return t, d\n"
    )})
    assert [(f.rule, f.line) for f in report.findings] == [("TW006", 3)]


def test_tw006_out_of_scope_files_unflagged(tmp_path):
    report = run(tmp_path, {"twtml_tpu/telemetry/clocky.py": (
        "import time\nNOW = []\n"
        "def sample():\n"
        "    NOW.append(time.time())\n"
    )})
    assert report.findings == []


def test_tw007_both_directions(tmp_path):
    report = run(tmp_path, {
        "twtml_tpu/config.py": (
            "class ConfArguments:\n"
            "    def parse(self, args):\n"
            "        flag = args[0]\n"
            "        if flag == '--foo':\n"
            "            pass\n"
            "        elif flag == '--undocumented':\n"
            "            pass\n"
            "        return self\n"
        ),
        "README.md": "Use `--foo` and the imaginary `--ghostFlag`.\n",
    })
    msgs = {f.rule: f for f in report.findings}
    assert set(msgs) == {"TW007"}
    texts = [f.message for f in report.findings]
    assert any("--undocumented" in t and "documented in neither" in t
               for t in texts)
    assert any("--ghostFlag" in t and "exists in no parser" in t
               for t in texts)
    # --ghostFlag anchors to the doc that mentions it
    assert any(f.path == "README.md" for f in report.findings)


# ---------------------------------------------------------------------------
# suppression semantics


_VIOLATION = (
    "import jax\n"
    "def f(out):\n"
    "    return jax.device_get(out){}\n"
)


def test_suppression_with_reason_silences(tmp_path):
    report = run(tmp_path, {"twtml_tpu/streaming/v.py": _VIOLATION.format(
        "  # lawcheck" ": disable=TW002 -- seeded test exemption"
    )})
    assert report.findings == [] and len(report.suppressed) == 1
    assert report.exit_code == 0


def test_suppression_without_reason_is_malformed(tmp_path):
    report = run(tmp_path, {"twtml_tpu/streaming/v.py": _VIOLATION.format(
        "  # lawcheck" ": disable=TW002"
    )})
    assert report.exit_code == 2
    assert any("without a reason" in m.message for m in report.malformed)


def test_suppression_unknown_rule_is_malformed(tmp_path):
    report = run(tmp_path, {"twtml_tpu/streaming/v.py": _VIOLATION.format(
        "  # lawcheck" ": disable=TW999 -- no such law"
    )})
    assert report.exit_code == 2
    assert any("unknown rule" in m.message for m in report.malformed)


def test_suppression_only_covers_its_own_line(tmp_path):
    report = run(tmp_path, {"twtml_tpu/streaming/v.py": (
        "import jax\n"
        "# lawcheck" ": disable=TW002 -- wrong line, must not apply below\n"
        "def f(out):\n"
        "    return jax.device_get(out)\n"
    )})
    assert [f.rule for f in report.findings] == ["TW002"]


def test_wrong_rule_suppression_does_not_silence(tmp_path):
    report = run(tmp_path, {"twtml_tpu/streaming/v.py": _VIOLATION.format(
        "  # lawcheck" ": disable=TW004 -- names the wrong law"
    )})
    assert [f.rule for f in report.findings] == ["TW002"]


# ---------------------------------------------------------------------------
# baseline semantics


def test_baseline_grandfathers_by_fingerprint(tmp_path):
    root = mini_repo(tmp_path, {
        "twtml_tpu/streaming/v.py": _VIOLATION.format(""),
    })
    bl = root / "baseline.json"
    bl.write_text(json.dumps(
        {"findings": ["TW002:twtml_tpu/streaming/v.py:3"]}
    ))
    report = engine.run_repo(root=str(root), baseline_path=str(bl))
    assert report.findings == [] and len(report.baselined) == 1
    assert report.exit_code == 0


def test_stale_baseline_entry_is_reported(tmp_path):
    root = mini_repo(tmp_path, {})
    bl = root / "baseline.json"
    bl.write_text(json.dumps({"findings": ["TW002:gone.py:1"]}))
    report = engine.run_repo(root=str(root), baseline_path=str(bl))
    assert report.stale_baseline == ["TW002:gone.py:1"]
    assert report.exit_code == 0  # stale entries don't fail, they nag


def test_corrupt_baseline_is_malformed(tmp_path):
    root = mini_repo(tmp_path, {})
    bl = root / "baseline.json"
    bl.write_text("{not json")
    report = engine.run_repo(root=str(root), baseline_path=str(bl))
    assert report.exit_code == 2


def test_unparsable_target_file_is_malformed(tmp_path):
    report = run(tmp_path, {"twtml_tpu/broken.py": "def f(:\n"})
    assert report.exit_code == 2
    assert any("cannot parse" in m.message for m in report.malformed)


# ---------------------------------------------------------------------------
# CLI contract: --json shape and exit codes


def _main(tmp_path, files, *extra):
    root = mini_repo(tmp_path, files)
    return engine.main([
        "--root", str(root), "--baseline", str(root / "baseline.json"),
        *extra,
    ])


def test_cli_exit_codes(tmp_path, capsys):
    assert _main(tmp_path / "clean", {}) == 0
    assert _main(tmp_path / "dirty", {
        "twtml_tpu/streaming/v.py": _VIOLATION.format(""),
    }) == 1
    assert _main(tmp_path / "malformed", {
        "twtml_tpu/broken.py": "def f(:\n",
    }) == 2
    capsys.readouterr()


def test_cli_json_mode(tmp_path, capsys):
    code = _main(tmp_path, {
        "twtml_tpu/streaming/v.py": _VIOLATION.format(""),
    }, "--json")
    out = json.loads(capsys.readouterr().out)
    assert code == 1 and out["exit_code"] == 1
    (finding,) = out["findings"]
    assert finding["rule"] == "TW002"
    assert finding["path"] == "twtml_tpu/streaming/v.py"
    assert finding["line"] == 3
    assert "FetchPipeline" in finding["message"]  # cites the seam law


def test_cli_list_rules_names_all_seven(tmp_path, capsys):
    assert engine.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in sorted(rule_ids()):
        assert rid in out


def test_write_baseline_roundtrip(tmp_path, capsys):
    files = {"twtml_tpu/streaming/v.py": _VIOLATION.format("")}
    assert _main(tmp_path, files, "--write-baseline") == 0
    capsys.readouterr()
    # the grandfathered finding no longer fails the gate
    assert _main(tmp_path, files) == 0


# ---------------------------------------------------------------------------
# registry + acceptance


def test_tw008_fires_on_fresh_pack_alloc(tmp_path):
    """r17 arena law: a pack-path function allocating its wire buffer
    fresh — np.empty, or np.concatenate without an out= destination —
    fires; the blessed arena-lease pattern right next to it stays
    quiet."""
    report = run(tmp_path, {"twtml_tpu/features/batch.py": (
        "import numpy as np\n"
        "from .arena import lease_wire\n"
        "def pack_batch(batch):\n"
        "    buf = np.empty((1024,), np.uint8)\n"        # fires
        "    return np.concatenate([buf, buf])\n"        # fires (no out=)
        "def pack_ragged_sharded(rb):\n"
        "    lease = lease_wire(2048)\n"
        "    out = lease.buf\n"
        "    np.concatenate([out[:1024], out[1024:]], out=out)\n"  # quiet
        "    return out\n"
        "def featurize_helper():\n"
        "    return np.zeros((64,), np.uint8)\n"          # out of scope
    )})
    lines = [f.line for f in report.findings if f.rule == "TW008"]
    assert lines == [4, 5]


def test_tw008_scoped_to_pack_hot_path(tmp_path):
    """The same allocations OUTSIDE the scoped modules (or outside
    pack-path functions) are not findings — the law covers the wire
    buffer the transport client retains, not every numpy call."""
    report = run(tmp_path, {"twtml_tpu/streaming/sources.py": (
        "import numpy as np\n"
        "def pack_batch(batch):\n"
        "    return np.empty((1024,), np.uint8)\n"
    )})
    assert "TW008" not in rules_fired(report)


def test_tw010_fires_on_historian_sampling_outside_the_seam(tmp_path):
    """ISSUE 20 law: historian.sample() may run ONLY from the SessionStats
    publish seam — a second sampling site pays new snapshot work on a hot
    path (or invites a device fetch the counted-fetch law forbids)."""
    report = run(tmp_path, {"twtml_tpu/streaming/context.py": (
        "from twtml_tpu.telemetry import historian as _historian\n"
        "def _lockstep_loop(self):\n"
        "    _historian.sample()\n"                      # fires
        "    _historian.get().sample()\n"                # fires too
    )})
    lines = [f.line for f in report.findings if f.rule == "TW010"]
    assert lines == [3, 4]


def test_tw010_quiet_in_the_seam_and_on_other_samples(tmp_path):
    report = run(tmp_path, {
        "twtml_tpu/telemetry/session_stats.py": (
            "from . import historian as _historian\n"
            "def publish_metrics(self):\n"
            "    _historian.sample()\n"                  # THE seam
        ),
        "twtml_tpu/streaming/sources.py": (
            "import random\n"
            "def pick(xs):\n"
            "    return random.sample(xs, 3)\n"          # not historian
        ),
    })
    assert "TW010" not in rules_fired(report)


def test_rule_registry_is_stable():
    rules = all_rules()
    ids = [r.id for r in rules]
    assert len(ids) == len(set(ids)) and len(ids) >= 7
    for r in rules:
        assert r.title and r.law, f"{r.id} must cite its measured law"


def test_repo_is_clean_with_empty_baseline():
    """THE acceptance criterion: the real checkout passes every law with
    nothing grandfathered — every remaining deviation is an inline
    suppression carrying its written reason."""
    report = engine.run_repo()
    assert [m.render() for m in report.malformed] == []
    assert [f.render() for f in report.findings] == []
    with open(engine._DEFAULT_BASELINE, encoding="utf-8") as fh:
        assert json.load(fh)["findings"] == []
    assert report.stale_baseline == []
    assert report.exit_code == 0
