"""Serving plane (ISSUE 9): parity, promotion gate, coalescer, hot-swap,
chaos, and the HTTP front door.

The read-path parity law: serve-path predictions must BIT-equal the fused
train step's reported predictions for the same snapshot and batch — the
train step predicts with PRE-update weights (predict-then-train,
LinearRegression.scala:85-86), and the predict-only program is that same
traced prologue with a zero-iteration loop (serving/engine.py). Every test
here runs the REAL plane (threads, FetchPipeline, watchdog) on the CPU
backend.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from twtml_tpu.config import ConfArguments  # noqa: E402
from twtml_tpu.features.featurizer import Featurizer  # noqa: E402
from twtml_tpu.models import (  # noqa: E402
    StreamingLinearRegressionWithSGD,
)
from twtml_tpu.serving import (  # noqa: E402
    ServingClient,
    ServingSnapshot,
    SnapshotPromoter,
    is_promotable,
    load_servable,
)
from twtml_tpu.serving.plane import ServingPlane  # noqa: E402
from twtml_tpu.streaming import faults  # noqa: E402
from twtml_tpu.streaming.sources import SyntheticSource  # noqa: E402
from twtml_tpu.telemetry import metrics as _metrics  # noqa: E402

NOW_MS = 1785320000000
CLOSED = "http://127.0.0.1:9"  # closed port: telemetry best-effort no-ops


@pytest.fixture(autouse=True)
def _clean():
    _metrics.reset_for_tests()
    faults.uninstall_chaos()
    yield
    faults.uninstall_chaos()
    _metrics.reset_for_tests()


def _statuses(n, seed=3):
    return list(SyntheticSource(total=n, seed=seed).produce())


def _feat():
    return Featurizer(now_ms=NOW_MS)


def _trained_weights(n=32, steps=1):
    """Non-trivial single-model weights from a short real training run."""
    import jax

    feat = _feat()
    model = StreamingLinearRegressionWithSGD()
    statuses = _statuses(n * steps, seed=11)
    for k in range(steps):
        b = feat.featurize_batch_ragged(
            statuses[k * n:(k + 1) * n], row_bucket=n, pre_filtered=True
        )
        jax.device_get(model.step(b))
    return model.latest_weights.copy()


def _plane(snapshot, **kw):
    kw.setdefault("featurizer", _feat())
    kw.setdefault("batch_rows", 32)
    kw.setdefault("max_wait_ms", 5.0)
    kw.setdefault("depth", 4)
    return ServingPlane(snapshot, **kw)


# ---------------------------------------------------------------------------
# the promotion predicate + gate tool

def test_is_promotable_predicate():
    ok, _ = is_promotable({"finite": True, "quality": {"level": "ok"}})
    assert ok
    ok, _ = is_promotable({"finite": True, "quality": {"level": "warn"}})
    assert ok  # warn serves
    ok, reason = is_promotable(
        {"finite": True, "quality": {"level": "alert", "drift_score": 9.0}}
    )
    assert not ok and "alert" in reason  # alert refuses
    ok, reason = is_promotable({"finite": False})
    assert not ok and "finite" in reason
    ok, reason = is_promotable({"finite": True})  # unstamped serves
    assert ok and "unstamped" in reason
    ok, _ = is_promotable(None)
    assert not ok


def _save_ckpt(directory, step, weights, level=None, finite_weights=True):
    from twtml_tpu.checkpoint import Checkpointer

    meta = {"count": step * 10, "batches": step}
    if level is not None:
        meta["quality"] = {"level": level, "drift_score": 5.0,
                           "loss_trend": 0.1}
    w = np.asarray(weights, np.float32)
    if not finite_weights:
        w = w.copy()
        w[0] = np.nan
    return Checkpointer(str(directory)).save(step, w, meta)


def test_model_report_gate_exit_codes(tmp_path):
    """--gate: 0 promotable, 1 not promotable, 2 malformed — running the
    serving plane's own predicate (the ops/server agreement law)."""
    from tools.model_report import main as report_main

    w = np.arange(1004, dtype=np.float32)
    ok_dir = tmp_path / "ok"
    _save_ckpt(ok_dir, 1, w, level="warn")
    assert report_main([str(ok_dir), "--gate"]) == 0

    alert_dir = tmp_path / "alert"
    _save_ckpt(alert_dir, 1, w, level="alert")
    assert report_main([str(alert_dir), "--gate"]) == 1

    # quarantined-only directory: archives exist but none is servable
    quar_dir = tmp_path / "quar"
    _save_ckpt(quar_dir, 1, w, level="ok", finite_weights=False)
    assert report_main([str(quar_dir), "--gate"]) == 1

    assert report_main([str(tmp_path / "missing"), "--gate"]) == 2

    # the gate's verdict IS load_servable's (one predicate, two faces)
    snap, _ = load_servable(str(alert_dir))
    assert snap is None
    snap, _ = load_servable(str(ok_dir))
    assert snap is not None and snap.step == 1 and snap.num_tenants == 1


def test_model_report_gate_json(tmp_path, capsys):
    from tools.model_report import main as report_main

    _save_ckpt(tmp_path / "d", 7, np.zeros(1004, np.float32), level="ok")
    assert report_main([str(tmp_path / "d"), "--gate", "--json"]) == 0
    verdict = json.loads(capsys.readouterr().out.strip())
    assert verdict["promotable"] is True and verdict["step"] == 7


# ---------------------------------------------------------------------------
# read-path parity: serve predictions BIT-equal the train step's

def test_serve_predictions_bit_equal_train_step():
    """THE parity law on the read path: for the same snapshot and batch,
    the plane's predictions are bitwise the fused train step's pre-update
    predictions (predict-then-train ordering + HALF_UP rounding included —
    it is literally the same traced prologue)."""
    import jax

    w = _trained_weights()
    statuses = _statuses(24, seed=5)
    snap = ServingSnapshot(step=3, weights=w,
                           meta={"quality": {"level": "ok"}})
    plane = _plane(snap).start()
    try:
        res = plane.submit(statuses).result(timeout=120)
    finally:
        plane.stop()
    got = np.asarray(res["predictions"], np.float32)
    assert res["snapshot_step"] == 3

    # ground truth: the TRAIN step on the identical featurized batch
    batch = _feat().featurize_batch_ragged(
        statuses, row_bucket=32, pre_filtered=True
    )
    ref_model = StreamingLinearRegressionWithSGD().set_initial_weights(w)
    out = jax.device_get(ref_model.step(batch))
    ref = np.asarray(out.predictions)[np.asarray(batch.mask) > 0]
    assert np.array_equal(ref, got)

    # ...and the train step MOVED its weights (so the parity above really
    # pinned the PRE-update predictions, not a no-op model)
    assert not np.array_equal(ref_model.latest_weights, w)
    # serving never moved the snapshot
    assert np.array_equal(
        np.asarray(plane._engine.model.latest_weights), w
    )


def test_serve_predictions_bit_equal_per_tenant_models():
    """Tenant-stack parity: an [M, F+4] snapshot serves every row with the
    SAME bits its tenant's standalone single model would produce, re-ordered
    to original request rows through the deterministic route."""
    import jax

    from twtml_tpu.features.batch import tenant_route_keys

    m_tenants = 4
    rng = np.random.default_rng(0)
    stack = (rng.standard_normal((m_tenants, 1004)) * 1e-3).astype(np.float32)
    statuses = _statuses(24, seed=9)
    snap = ServingSnapshot(step=5, weights=stack,
                           meta={"quality": {"level": "ok"}})
    plane = _plane(snap).start()
    try:
        res = plane.submit(statuses).result(timeout=240)
    finally:
        plane.stop()
    got = np.asarray(res["predictions"], np.float32)
    assert got.shape == (24,)

    batch = _feat().featurize_batch_ragged(
        statuses, row_bucket=32, pre_filtered=True
    )
    route = tenant_route_keys(batch, m_tenants)
    assert len(set(route[:24].tolist())) > 1  # the split actually split
    ref = np.zeros(24, np.float32)
    for m in range(m_tenants):
        model = StreamingLinearRegressionWithSGD().set_initial_weights(
            stack[m]
        )
        out = jax.device_get(model.step(batch))
        preds = np.asarray(out.predictions)
        rows = np.nonzero(route[:24] == m)[0]
        ref[rows] = preds[rows]
    assert np.array_equal(ref, got)


# ---------------------------------------------------------------------------
# coalescer semantics

def test_coalescer_one_dispatch_for_queued_requests():
    """Requests queued together ride ONE dispatch (the whole point: one
    featurize + one device program + one fetch per coalesced batch), and
    each future gets exactly its own rows back."""
    w = np.zeros(1004, np.float32)
    snap = ServingSnapshot(step=1, weights=w)
    plane = _plane(snap, batch_rows=64, max_wait_ms=20.0)
    steps = []
    real_step = plane._engine.model.step

    def counting_step(wire):
        steps.append(1)
        return real_step(wire)

    plane._engine.model.step = counting_step
    futs = [plane.submit(_statuses(8, seed=s)) for s in range(4)]
    plane.start()  # queued BEFORE the loop runs → one group, one dispatch
    try:
        results = [f.result(timeout=120) for f in futs]
    finally:
        plane.stop()
    assert len(steps) == 1
    assert all(len(r["predictions"]) == 8 for r in results)
    assert _metrics.get_registry().counter("serve.batches").snapshot() == 1
    assert _metrics.get_registry().counter("serve.requests").snapshot() == 4


def test_partial_batch_dispatches_after_bounded_wait():
    """A lone sub-bucket request must not wait for the bucket to fill —
    the --serveMaxWaitMs bound dispatches the partial batch."""
    snap = ServingSnapshot(step=1, weights=np.zeros(1004, np.float32))
    plane = _plane(snap, batch_rows=256, max_wait_ms=10.0).start()
    try:
        res = plane.submit(_statuses(4)).result(timeout=120)
    finally:
        plane.stop()
    assert len(res["predictions"]) == 4


def test_oversized_and_empty_requests():
    snap = ServingSnapshot(step=1, weights=np.zeros(1004, np.float32))
    plane = _plane(snap, batch_rows=8).start()
    try:
        with pytest.raises(ValueError, match="serveBatchRows"):
            plane.submit(_statuses(9)).result(timeout=10)
        assert plane.submit([]).result(timeout=10)["predictions"] == []
    finally:
        plane.stop()


def test_statuses_from_rows_faces():
    rows = [
        "bare text",
        {"text": "plain", "followers_count": 10, "created_at_ms": NOW_MS},
        {"text": "rt wrapper ignored", "retweeted_status": {
            "text": "original", "retweet_count": 7,
            "user": {"followers_count": 3}, "timestamp_ms": str(NOW_MS),
        }},
    ]
    statuses = ServingPlane.statuses_from_rows(rows)
    assert [s.retweeted_status.text for s in statuses] == [
        "bare text", "plain", "original",
    ]
    assert statuses[1].retweeted_status.followers_count == 10
    assert statuses[1].retweeted_status.created_at_ms == NOW_MS
    assert statuses[2].retweeted_status.retweet_count == 7
    with pytest.raises(ValueError):
        ServingPlane.statuses_from_rows([42])


# ---------------------------------------------------------------------------
# snapshot promotion + atomic hot-swap

def test_promoter_promotes_ok_and_refuses_alert(tmp_path):
    import jax

    ck = tmp_path / "ck"
    w1 = np.zeros(1004, np.float32)
    _save_ckpt(ck, 1, w1, level="ok")
    snap, reason = load_servable(str(ck))
    assert snap is not None and "ok" in reason
    plane = _plane(snap).start()
    promoter = SnapshotPromoter(str(ck), plane, poll_s=30.0)
    try:
        # an alert-stamped newer checkpoint is REFUSED; serving stays put
        w2 = np.full(1004, 0.5, np.float32)
        _save_ckpt(ck, 2, w2, level="alert")
        assert promoter.poll_once() is False
        assert plane.snapshot_step == 1
        assert _metrics.get_registry().counter(
            "serve.promotions_refused").snapshot() == 1

        # a healthy newer checkpoint hot-swaps in. poll_once STAGES the
        # swap; the serve loop applies it between dispatches (the atomic-
        # swap contract), so give its next tick a bounded moment to land
        w3 = np.full(1004, 0.25, np.float32)
        _save_ckpt(ck, 3, w3, level="warn")
        assert promoter.poll_once() is True
        deadline = time.monotonic() + 10
        while plane.snapshot_step != 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert plane.snapshot_step == 3

        # served predictions now come from w3 (swap really landed)
        statuses = _statuses(8)
        res = plane.submit(statuses).result(timeout=120)
        assert res["snapshot_step"] == 3
        batch = _feat().featurize_batch_ragged(
            statuses, row_bucket=32, pre_filtered=True
        )
        ref_model = StreamingLinearRegressionWithSGD().set_initial_weights(w3)
        ref = np.asarray(jax.device_get(ref_model.step(batch)).predictions)[
            np.asarray(batch.mask) > 0
        ]
        assert np.array_equal(ref, np.asarray(res["predictions"], np.float32))
    finally:
        promoter.stop()
        plane.stop()


def test_hot_swap_under_load_tears_nothing():
    """Hot-swap while requests stream: every request resolves, and each
    response's predictions match EXACTLY the snapshot its reported step
    names — never a half-applied mix (the atomic-swap law)."""
    import jax

    statuses = _statuses(8, seed=21)
    batch = _feat().featurize_batch_ragged(
        statuses, row_bucket=32, pre_filtered=True
    )
    refs = {}
    w_a = np.zeros(1004, np.float32)
    w_b = (np.arange(1004) % 7).astype(np.float32) * 1e-3
    for step, w in ((1, w_a), (2, w_b)):
        model = StreamingLinearRegressionWithSGD().set_initial_weights(w)
        out = jax.device_get(model.step(batch))
        refs[step] = np.asarray(out.predictions)[
            np.asarray(batch.mask) > 0
        ]

    plane = _plane(
        ServingSnapshot(step=1, weights=w_a), max_wait_ms=0.5,
    ).start()
    plane.warmup()
    results = []
    errors = []

    def loader():
        try:
            for _ in range(10):
                results.append(
                    plane.submit(list(statuses)).result(timeout=120)
                )
        except Exception as exc:  # pragma: no cover - failure evidence
            errors.append(exc)

    threads = [threading.Thread(target=loader) for _ in range(3)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.1)
        plane.hot_swap(ServingSnapshot(step=2, weights=w_b))
        for t in threads:
            t.join(timeout=180)
    finally:
        plane.stop()
    assert not errors
    assert len(results) == 30  # zero requests lost
    seen_steps = set()
    for res in results:
        step = res["snapshot_step"]
        seen_steps.add(step)
        # the predictions must be EXACTLY the reported snapshot's — a torn
        # swap would produce a vector matching neither reference
        assert np.array_equal(
            refs[step], np.asarray(res["predictions"], np.float32)
        ), f"response torn across snapshots (claimed step {step})"
    assert 2 in seen_steps  # the swap actually served traffic


# ---------------------------------------------------------------------------
# chaos: the serve path trips the existing guards, never hangs a client

def test_chaos_fetch_error_trips_watchdog_not_client_hang(monkeypatch):
    monkeypatch.setenv("TWTML_FETCH_DEADLINE_S", "0.5")
    monkeypatch.setenv("TWTML_FETCH_RETRIES", "1")
    faults.install_chaos("fetch:error@1")
    snap = ServingSnapshot(step=1, weights=np.zeros(1004, np.float32))
    plane = _plane(snap).start()
    try:
        fut = plane.submit(_statuses(4))
        with pytest.raises(RuntimeError, match="watchdog|abort"):
            fut.result(timeout=120)
        assert plane.failed
        # the guard machinery fired: retries then a counted abort
        assert _metrics.get_registry().counter(
            "fetch.aborts").snapshot() == 1
        assert _metrics.get_registry().counter(
            "fetch.retries").snapshot() >= 1
        assert _metrics.get_registry().counter(
            "serve.errors").snapshot() >= 1
        # subsequent submits fail FAST (no queue into a dead plane)
        with pytest.raises(RuntimeError, match="aborted"):
            plane.submit(_statuses(2)).result(timeout=10)
    finally:
        faults.uninstall_chaos()
        plane.stop()


def test_idle_stalled_fetch_reissues_and_recovers(monkeypatch):
    """The idle-server wedged-fetch case: ONE stalled fetch with no
    follow-up traffic must still hit the watchdog deadline (the serve
    loop's poll path enforces it), re-issue — a device_get is an RTT-bound
    request, the r3 law — and the request completes instead of hanging
    until the next request arrives."""
    monkeypatch.setenv("TWTML_FETCH_DEADLINE_S", "0.3")
    monkeypatch.setenv("TWTML_FETCH_RETRIES", "3")
    import jax

    from twtml_tpu.serving.engine import PredictEngine

    engine = PredictEngine(num_text_features=1000)
    stalled = {"n": 0}

    def one_shot_stall(out):
        host = jax.device_get(out)
        stalled["n"] += 1
        if stalled["n"] == 1:  # only the FIRST fetch wedges
            time.sleep(1.2)
        return host

    engine.fetch_output = one_shot_stall
    snap = ServingSnapshot(step=1, weights=np.zeros(1004, np.float32))
    plane = _plane(snap, engine=engine).start()
    try:
        res = plane.submit(_statuses(4)).result(timeout=120)
        assert len(res["predictions"]) == 4
        assert not plane.failed
        assert _metrics.get_registry().counter(
            "fetch.retries").snapshot() >= 1
    finally:
        plane.stop()


# ---------------------------------------------------------------------------
# zero added train-path fetches + train bit-identity with serving live

def _write_replay(tmp_path, n, seed=31):
    path = tmp_path / "tweets.jsonl"
    with open(path, "w", encoding="utf-8") as fh:
        for s in SyntheticSource(total=n, seed=seed, base_ms=NOW_MS).produce():
            d = {
                "text": s.text, "retweet_count": s.retweet_count,
                "user": {"followers_count": s.followers_count,
                         "favourites_count": s.favourites_count,
                         "friends_count": s.friends_count},
                "timestamp_ms": str(s.created_at_ms), "lang": s.lang or "en",
            }
            if s.retweeted_status is not None:
                r = s.retweeted_status
                d["retweeted_status"] = {
                    "text": r.text, "retweet_count": r.retweet_count,
                    "user": {"followers_count": r.followers_count,
                             "favourites_count": r.favourites_count,
                             "friends_count": r.friends_count},
                    "timestamp_ms": str(r.created_at_ms),
                }
            fh.write(json.dumps(d) + "\n")
    return path


def test_serving_adds_zero_train_fetches_and_keeps_training_bit_identical(
    tmp_path, monkeypatch
):
    """ACCEPTANCE: with a serving plane + promoter live against the train
    run's checkpoint directory, the train path still fetches exactly once
    per batch (promotion is DISK-only), and the trained weights are
    bit-identical to a run with no serving at all."""
    import jax

    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.checkpoint import Checkpointer

    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    path = _write_replay(tmp_path, 8 * 16)
    base = [
        "--source", "replay", "--replayFile", str(path),
        "--seconds", "0", "--backend", "cpu", "--master", "local[1]",
        "--batchBucket", "16", "--tokenBucket", "64",
        "--lightning", CLOSED, "--twtweb", CLOSED, "--webTimeout", "0.2",
    ]

    # control run: no serving anywhere
    ck_a = str(tmp_path / "ck_a")
    app.run(ConfArguments().parse(
        base + ["--checkpointDir", ck_a, "--checkpointEvery", "2"]
    ))
    control_state, control_meta = Checkpointer(ck_a).restore()

    # serving-live run: plane + promoter polling the ckpt dir mid-train
    ck_b = str(tmp_path / "ck_b")
    os.makedirs(ck_b)
    _save_ckpt(ck_b, 0, np.zeros(1004, np.float32), level="ok")
    snap, _ = load_servable(ck_b)
    plane = _plane(snap).start()
    promoter = SnapshotPromoter(ck_b, plane, poll_s=0.05).start()
    calls = {"n": 0}
    real_get = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real_get(x)

    jax.device_get = counting
    try:
        totals = app.run(ConfArguments().parse(
            base + ["--checkpointDir", ck_b, "--checkpointEvery", "2"]
        ))
    finally:
        jax.device_get = real_get
    assert totals["batches"] == 8
    assert calls["n"] == 8  # ONE fetch per train batch — serving added none
    # the promoter reached the train run's newest verified checkpoint.
    # Promotion is STAGED (poll) and applied between serve-loop dispatches
    # (the atomic-swap contract), so wait boundedly for the swap to land
    deadline = time.monotonic() + 10
    while plane.snapshot_step != totals["batches"] and (
        time.monotonic() < deadline
    ):
        promoter.poll_once()
        time.sleep(0.01)
    assert plane.snapshot_step == totals["batches"]
    promoter.stop()
    plane.stop()

    # bit-identity: identical final weights + counters either way
    serving_state, serving_meta = Checkpointer(ck_b).restore()
    assert serving_meta["count"] == control_meta["count"]
    assert np.array_equal(np.asarray(control_state),
                          np.asarray(serving_state))


# ---------------------------------------------------------------------------
# the HTTP front door + the serve entry point

def test_http_predict_roundtrip_and_503_without_plane(tmp_path):
    import urllib.request

    from twtml_tpu.serving.client import ServingError
    from twtml_tpu.web.cache import ApiCache
    from twtml_tpu.web.server import Server

    # no plane attached → 503 with a JSON error
    bare = Server(port=0, host="127.0.0.1",
                  cache=ApiCache(backup_file=str(tmp_path / "c1.json")))
    bare.start_background()
    try:
        url = f"http://127.0.0.1:{bare._runner.addresses[0][1]}"
        with pytest.raises(ServingError) as exc_info:
            ServingClient(url).predict([{"text": "x"}])
        assert exc_info.value.status == 503
    finally:
        bare.stop()

    w = _trained_weights()
    snap = ServingSnapshot(step=9, weights=w,
                           meta={"quality": {"level": "ok"}})
    plane = _plane(snap).start()
    srv = Server(port=0, host="127.0.0.1",
                 cache=ApiCache(backup_file=str(tmp_path / "c2.json")))
    srv.attach_serving(plane)
    srv.start_background()
    try:
        url = f"http://127.0.0.1:{srv._runner.addresses[0][1]}"
        client = ServingClient(url)
        res = client.predict([
            {"text": "served over http", "followers_count": 5,
             "created_at_ms": NOW_MS},
            "bare string row",
        ])
        assert res["snapshotStep"] == 9 and res["servedRows"] == 2
        assert len(res["predictions"]) == 2

        # a malformed body is a 400, not a 500/hang
        req = urllib.request.Request(
            url + "/api/predict", data=b'{"rows": 7}',
            headers={"content-type": "application/json"}, method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as http_err:
            urllib.request.urlopen(req, timeout=5)
        assert http_err.value.code == 400

        # /api/serving: default view, then the published plane stats
        view = client.serving()
        assert view["jsonClass"] == "Serving" and view["snapshotStep"] == -1
        from twtml_tpu.telemetry.web_client import WebClient

        WebClient(url).serving(plane.stats())
        view = client.serving()
        assert view["snapshotStep"] == 9 and view["requests"] == 1
        assert view["level"] == "ok"
    finally:
        srv.stop()
        plane.stop()


def test_serve_app_end_to_end(tmp_path, monkeypatch):
    """The CI serve-smoke: boot apps.serve against a trained checkpoint
    directory, round-trip one predict over real HTTP, assert parity."""
    import jax

    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    ck = tmp_path / "ck"
    w = _trained_weights()
    _save_ckpt(ck, 4, w, level="ok")

    from twtml_tpu.apps import serve as serve_app

    stop = threading.Event()
    ready = {}
    ready_evt = threading.Event()

    def started(server, plane, promoter):
        ready["port"] = server._runner.addresses[0][1]
        ready_evt.set()

    conf = ConfArguments().parse([
        "--backend", "cpu", "--master", "local[1]",
        "--checkpointDir", str(ck), "--servePort", "0",
        "--serveBatchRows", "32", "--serveMaxWaitMs", "2",
        "--servePromoteEvery", "600",
    ])
    result = {}

    def runner():
        result["stats"] = serve_app.run(conf, started=started,
                                        stop_event=stop)

    thread = threading.Thread(target=runner)
    thread.start()
    try:
        assert ready_evt.wait(timeout=300), "serve app never came up"
        client = ServingClient(f"http://127.0.0.1:{ready['port']}")
        statuses = _statuses(6, seed=2)
        rows = [{
            "text": s.retweeted_status.text,
            "followers_count": s.retweeted_status.followers_count,
            "favourites_count": s.retweeted_status.favourites_count,
            "friends_count": s.retweeted_status.friends_count,
            "created_at_ms": s.retweeted_status.created_at_ms,
        } for s in statuses]
        res = client.predict(rows)
        assert res["snapshotStep"] == 4 and res["servedRows"] == 6
    finally:
        stop.set()
        thread.join(timeout=120)
    assert not thread.is_alive()
    assert result["stats"]["requests"] == 1

    # parity through the full HTTP + JSON + plane stack
    batch = _feat().featurize_batch_ragged(
        statuses, row_bucket=32, pre_filtered=True
    )
    ref_model = StreamingLinearRegressionWithSGD().set_initial_weights(w)
    ref = np.asarray(jax.device_get(ref_model.step(batch)).predictions)[
        np.asarray(batch.mask) > 0
    ]
    assert np.array_equal(ref, np.asarray(res["predictions"], np.float32))


def test_serve_app_refuses_unservable_directory(tmp_path):
    from twtml_tpu.apps import serve as serve_app

    conf = ConfArguments().parse([
        "--backend", "cpu", "--checkpointDir", str(tmp_path / "nope"),
    ])
    with pytest.raises(SystemExit, match="no servable snapshot"):
        serve_app.run(conf)
    with pytest.raises(SystemExit, match="checkpointDir"):
        serve_app.run(ConfArguments().parse(["--backend", "cpu"]))


# ---------------------------------------------------------------------------
# telemetry view

def test_stats_view_shape_and_tenant_tiles():
    rng = np.random.default_rng(1)
    stack = (rng.standard_normal((2, 1004)) * 1e-3).astype(np.float32)
    snap = ServingSnapshot(step=2, weights=stack,
                           meta={"quality": {"level": "warn"}})
    plane = _plane(snap).start()
    try:
        plane.submit(_statuses(16)).result(timeout=240)
        view = plane.stats()
    finally:
        plane.stop()
    assert view["snapshotStep"] == 2 and view["level"] == "warn"
    assert view["requests"] == 1 and view["rows"] == 16
    assert view["qps"] > 0 and view["p99Ms"] > 0
    assert [t["tenant"] for t in view["tenants"]] == [0, 1]
    assert sum(t["rows"] for t in view["tenants"]) == 16
    # the view round-trips the Serving jsonClass wire
    from twtml_tpu.telemetry.api_types import decode, encode, Serving

    known = Serving.__dataclass_fields__
    msg = Serving(**{k: v for k, v in view.items() if k in known})
    back = decode(encode(msg))
    assert back == msg and back.tenants == view["tenants"]
