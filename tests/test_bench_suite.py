"""The BASELINE-config benchmark suite must measure every config (tiny
sizes here; the numbers are irrelevant, the plumbing is what's tested)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools"))

import bench_suite


def test_replay_linear_measures():
    rec = bench_suite.run_config("replay_linear", 512, 256)
    assert rec["tweets_per_sec"] > 0 and rec["batches"] == 2
    assert rec["backend"] == "cpu"


def test_logistic_sentiment_measures():
    rec = bench_suite.run_config("logistic_sentiment", 512, 256)
    assert rec["tweets_per_sec"] > 0
    assert 0.0 <= rec["final_metric"] <= 1.0  # misclassification rate


def test_hashing_2e18_l2_uses_sparse_path():
    rec = bench_suite.run_config("hashing_2e18_l2", 512, 256)
    assert rec["tweets_per_sec"] > 0


def test_sharded_dp4_runs_on_virtual_mesh():
    # conftest provides 8 virtual CPU devices
    rec = bench_suite.run_config("sharded_dp4", 512, 256)
    assert rec["tweets_per_sec"] > 0


def test_sharded_2e18_2d_runs_on_virtual_mesh():
    rec = bench_suite.run_config("sharded_2e18_2d", 256, 128)
    assert rec["tweets_per_sec"] > 0


def test_twitter_live_measures_local_protocol_without_creds(clean_properties):
    """Without creds, config #2 measures the REAL TwitterSource → train
    path against the in-process v1.1 server (VERDICT r2 #6), tagged so it
    is never confused with real Twitter."""
    rec = bench_suite.run_config("twitter_live", 64, 64)
    assert rec["mode"] == "local-protocol"
    assert rec["tweets_per_sec"] > 0
    assert rec["protocol_tweets_per_sec"] > 0
    assert rec["batches"] >= 1
