"""The BASELINE-config benchmark suite must measure every config (tiny
sizes here; the numbers are irrelevant, the plumbing is what's tested)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools"))

import bench_suite


def test_replay_linear_measures():
    rec = bench_suite.run_config("replay_linear", 512, 256)
    assert rec["tweets_per_sec"] > 0 and rec["batches"] == 2
    assert rec["backend"] == "cpu"


def test_logistic_sentiment_measures():
    rec = bench_suite.run_config("logistic_sentiment", 512, 256)
    assert rec["tweets_per_sec"] > 0
    assert 0.0 <= rec["final_metric"] <= 1.0  # misclassification rate


def test_hashing_2e18_l2_uses_sparse_path():
    rec = bench_suite.run_config("hashing_2e18_l2", 512, 256)
    assert rec["tweets_per_sec"] > 0


def test_sharded_dp4_runs_on_virtual_mesh():
    # conftest provides 8 virtual CPU devices
    rec = bench_suite.run_config("sharded_dp4", 512, 256)
    assert rec["tweets_per_sec"] > 0


def test_sharded_2e18_2d_runs_on_virtual_mesh():
    rec = bench_suite.run_config("sharded_2e18_2d", 256, 128)
    assert rec["tweets_per_sec"] > 0


def test_wire_codec_measures():
    """The compressed-wire config (ISSUE 12) must run both windows (CPU
    control + modeled upload-bound) and report the paired ratios and the
    wire/units compression — plumbing only, tiny sizes."""
    rec = bench_suite.run_config("wire_codec", 2048, 512)
    assert rec["wire_ratio"] >= 1.0
    assert rec["units_ratio"] >= 1.0
    assert rec["paired_codec_cpu_control"] > 0
    assert rec["paired_codec_upload55"] > 0
    assert rec["paired_group_codec_upload55"] > 0


def test_featurize_measures():
    """The one-pass featurize config (ISSUE 15) must run both regimes
    and report the paired ratios plus the sub-stage split — plumbing
    only, tiny sizes."""
    rec = bench_suite.run_config("featurize", 2048, 512)
    assert rec["paired_fused_vs_r17"] > 0
    assert rec["paired_truth_vs_r17"] > 0
    assert rec["tweets_per_sec_fused"] > 0
    assert rec["paired_block_chain"] > 0
    assert rec["block_chain_tweets_per_sec"] > 0


def test_twitter_live_measures_local_protocol_without_creds(clean_properties):
    """Without creds, config #2 measures the REAL TwitterSource → train
    path against the in-process v1.1 server (VERDICT r2 #6), tagged so it
    is never confused with real Twitter."""
    rec = bench_suite.run_config("twitter_live", 64, 64)
    assert rec["mode"] == "local-protocol"
    assert rec["tweets_per_sec"] > 0
    assert rec["protocol_tweets_per_sec"] > 0
    assert rec["batches"] >= 1


def test_bench_meshpack_smoke(capsys):
    """The mesh-pack paired bench (tools/bench_meshpack.py, r5) must run on
    the virtual CPU mesh: both arms execute through ParallelSGDModel and
    the tool itself asserts per-round final-mse bit-identity between the
    packed and unpacked wire — a CI-side guard for the pack_for_wire
    path on a multi-device data axis."""
    import json

    import bench_meshpack

    bench_meshpack.main(
        ["--devices", "2", "--tweets", "2048", "--batch", "1024",
         "--budget", "0.5"]
    )
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["devices"] == 2 and rec["rounds"] >= 1
    assert rec["final_mse_bit_identical"] is True
    assert rec["packed"]["paired_speedup_vs_unpacked"] > 0
