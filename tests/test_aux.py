"""Aux subsystems: checkpoint/resume, fault injection, tracing hooks — the
upgrades SURVEY.md §5 calls out as absent in the reference."""

import os

import numpy as np
import pytest

from twtml_tpu.checkpoint import Checkpointer
from twtml_tpu.config import ConfArguments
from twtml_tpu.features.featurizer import Status
from twtml_tpu.streaming.faults import FaultInjectingSource
from twtml_tpu.streaming.sources import SyntheticSource
from twtml_tpu.utils.tracing import Tracer

DATA = os.path.join(os.path.dirname(__file__), "data", "tweets.jsonl")


class TestCheckpointer:
    def test_save_restore_roundtrip(self, tmp_path):
        ckpt = Checkpointer(str(tmp_path))
        w = np.arange(10, dtype=np.float32)
        ckpt.save(5, w, {"count": 123})
        restored, meta = ckpt.restore()
        np.testing.assert_array_equal(restored, w)
        assert meta["count"] == 123 and meta["step"] == 5

    def test_pytree_weights(self, tmp_path):
        ckpt = Checkpointer(str(tmp_path))
        ckpt.save(1, {"text": np.ones(4), "num": np.zeros(2)})
        restored, _ = ckpt.restore()
        assert set(restored) == {"text", "num"}
        np.testing.assert_array_equal(restored["text"], np.ones(4))

    def test_keep_last_prunes(self, tmp_path):
        ckpt = Checkpointer(str(tmp_path), keep_last=2)
        for step in range(5):
            ckpt.save(step, np.array([float(step)]))
        assert ckpt.latest_step() == 4
        files = [n for n in os.listdir(tmp_path) if n.endswith(".npz")]
        assert len(files) == 2
        restored, meta = ckpt.restore()
        assert meta["step"] == 4

    def test_corrupt_latest_falls_back(self, tmp_path):
        ckpt = Checkpointer(str(tmp_path))
        ckpt.save(1, np.array([1.0]))
        ckpt.save(2, np.array([2.0]))
        # corrupt the newest file
        newest = sorted(tmp_path.glob("ckpt-*.npz"))[-1]
        newest.write_bytes(b"garbage")
        restored, meta = ckpt.restore()
        assert meta["step"] == 1
        np.testing.assert_array_equal(restored, [1.0])

    def test_restore_empty_dir(self, tmp_path):
        assert Checkpointer(str(tmp_path)).restore() is None


class TestFaultInjection:
    def test_crash_every_n_and_recovery(self):
        import time

        inner = SyntheticSource(total=50, seed=1)
        src = FaultInjectingSource(inner, crash_every=20, max_crashes=2)
        got = []
        src.start(got.append)
        deadline = time.time() + 10
        while not src.exhausted and time.time() < deadline:
            time.sleep(0.01)
        src.stop()
        assert src.exhausted, "stream must complete after bounded crashes"
        assert src.crashes == 2  # crashed at 20 and 40, restarted both times
        assert len(got) >= 50  # all tweets eventually delivered (some dup'd
        # on restart since the synthetic stream restarts its generator)

    def test_finite_replay_with_faults_completes(self):
        """Regression: deterministic crashing must not livelock a finite
        replay file (crash cap lets the last run reach EOF)."""
        import time

        from twtml_tpu.streaming.sources import ReplayFileSource

        src = FaultInjectingSource(
            ReplayFileSource(DATA), crash_every=4, max_crashes=3
        )
        got = []
        src.start(got.append)
        deadline = time.time() + 10
        while not src.exhausted and time.time() < deadline:
            time.sleep(0.01)
        src.stop()
        assert src.exhausted
        assert src.crashes == 3
        assert len(got) >= 10  # full file delivered on the clean final run


class TestAppResume:
    def test_linear_app_checkpoints_and_resumes(self, tmp_path, capsys):
        from twtml_tpu.apps.linear_regression import run

        def conf(*extra):
            return ConfArguments().parse([
                "--source", "replay", "--replayFile", DATA,
                "--seconds", "1", "--backend", "cpu",
                "--checkpointDir", str(tmp_path), "--checkpointEvery", "1",
                "--lightning", "http://127.0.0.1:9",
                "--twtweb", "http://127.0.0.1:9",
                *extra,
            ])

        first = run(conf())
        assert first["count"] == 6
        ckpt = Checkpointer(str(tmp_path))
        weights_after_first, meta = ckpt.restore()
        assert meta["count"] == 6
        assert np.abs(weights_after_first).sum() > 0

        # second run over the SAME corpus is an EXACT resume (r21): with
        # --checkpointDir the intake journal is auto-on, the boot replay
        # fast-forwards past every journaled row the restored checkpoint
        # already covers, and nothing double-trains — counters and
        # weights are unchanged
        second = run(conf())
        assert second["count"] == 6
        weights_after_second, meta2 = ckpt.restore()
        assert meta2["count"] == 6
        np.testing.assert_array_equal(
            weights_after_first, weights_after_second
        )
        out = capsys.readouterr().out
        assert "count: 6" in out

        # --journal off restores the pre-r21 resume semantics bit-exactly:
        # the corpus re-trains on top of the restored counters
        third = run(conf("--journal", "off"))
        assert third["count"] == 12
        out = capsys.readouterr().out
        assert "count: 12" in out

    def test_logistic_app_checkpoints_and_resumes(self, tmp_path):
        """--checkpointDir works on every SGD entry point, not just the
        flagship (shared AppCheckpoint wiring, apps/common.py)."""
        from twtml_tpu.apps.logistic_regression import run

        def conf():
            return ConfArguments().parse([
                "--source", "replay", "--replayFile", DATA,
                "--seconds", "1", "--backend", "cpu",
                "--checkpointDir", str(tmp_path), "--checkpointEvery", "1",
                "--lightning", "http://127.0.0.1:9",
                "--twtweb", "http://127.0.0.1:9",
            ])

        first = run(conf())
        assert first["count"] == 6
        weights_after_first, meta = Checkpointer(str(tmp_path)).restore()
        assert meta["count"] == 6
        # exact resume (r21): same corpus + auto-on journal = no new rows
        second = run(conf())
        assert second["count"] == 6

    def test_kmeans_app_checkpoints_and_resumes(self, tmp_path):
        """Cluster state (centers + decay weights) checkpoints and resumes;
        a resumed run continues from the saved centers, not fresh randoms."""
        from twtml_tpu.apps.kmeans import run

        def conf():
            return ConfArguments().parse([
                "--source", "replay", "--replayFile", DATA,
                "--seconds", "1", "--backend", "cpu",
                "--checkpointDir", str(tmp_path), "--checkpointEvery", "1",
                "--lightning", "http://127.0.0.1:9",
                "--twtweb", "http://127.0.0.1:9",
            ])

        first = run(conf())
        assert first["count"] > 0
        state, meta = Checkpointer(str(tmp_path)).restore()
        assert set(state) == {"centers", "weights"}
        assert meta["batches"] == first["batches"]
        second = run(conf())
        assert second["count"] == 2 * first["count"]
        state2, _ = Checkpointer(str(tmp_path)).restore()
        # decay weights kept accumulating across the resume
        assert np.sum(state2["weights"]) > np.sum(state["weights"])


class TestTracer:
    def test_disabled_tracer_is_noop(self):
        with Tracer("") as t:
            assert not t.enabled

    def test_enabled_tracer_writes_trace(self, tmp_path):
        import jax.numpy as jnp

        with Tracer(str(tmp_path)):
            (jnp.arange(8.0) * 2).block_until_ready()
        produced = list(tmp_path.rglob("*"))
        assert produced, "no trace files written"


class TestLightningClient:
    """Protocol-level tests of the Lightning REST client (telemetry/
    lightning.py) against an in-process capture server — the vendored
    lightning-scala jar's API surface incl. the scatter-streaming chart the
    reference sketches at KMeans.scala:89,129-132."""

    @pytest.fixture()
    def server(self):
        import http.server
        import json as _json
        import threading

        calls = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                body = self.rfile.read(int(self.headers.get("content-length", 0)))
                calls.append((self.path, _json.loads(body or b"{}")))
                self.send_response(200)
                self.send_header("content-type", "application/json")
                self.end_headers()
                self.wfile.write(b'{"id": "42"}')

            def log_message(self, *a):
                pass

        srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        yield f"http://127.0.0.1:{srv.server_port}", calls
        srv.shutdown()

    def test_line_streaming_create_and_append(self, server):
        from twtml_tpu.telemetry.lightning import Lightning

        host, calls = server
        lgn = Lightning(host=host)
        viz = lgn.line_streaming([[0.0]] * 2, size=[1.0, 2.0])
        assert viz.id == "42"
        assert calls[0][0] == "/sessions/"
        assert calls[1][0] == "/sessions/42/visualizations/"
        assert calls[1][1]["type"] == "line-streaming"
        assert calls[1][1]["data"]["size"] == [1.0, 2.0]
        lgn.line_streaming([[1.0], [2.0]], viz=viz)
        assert calls[2][0] == "/visualizations/42/data/"
        assert calls[2][1]["data"]["series"] == [[1.0], [2.0]]

    def test_scatter_streaming_create_and_append(self, server):
        from twtml_tpu.telemetry.lightning import Lightning

        host, calls = server
        lgn = Lightning(host=host)
        viz = lgn.scatter_streaming([], [])
        assert calls[-1][1]["type"] == "scatter-streaming"
        lgn.scatter_streaming([1.0, 2.0], [3.0, 4.0], label=[0, 1], viz=viz)
        path, payload = calls[-1]
        assert path == "/visualizations/42/data/"
        assert payload["data"] == {"x": [1.0, 2.0], "y": [3.0, 4.0], "label": [0, 1]}


def test_rss_watchdog_warns_on_growth(caplog):
    """utils/rss.py: the watchdog samples on its tick cadence and warns at
    each threshold step of growth — the r4 guard for the axon-client
    transfer-buffer retention (BENCHMARKS.md r3 soak)."""
    import logging

    from twtml_tpu.utils import rss as rss_mod

    wd = rss_mod.RssWatchdog(warn_growth_mb=100.0, sample_every=2)
    samples = iter([1000.0, 1050.0, 1101.0, 1140.0, 1250.0])
    orig = rss_mod.rss_mb
    rss_mod.rss_mb = lambda: next(samples)
    try:
        with caplog.at_level(logging.WARNING, logger="twtml_tpu.utils.rss"):
            for _ in range(10):
                wd.tick()
    finally:
        rss_mod.rss_mb = orig
    # growth crossed 100 MB at sample 3 (1101) and the next step at 1250
    assert wd.warn_count == 2
    assert wd.last_mb == 1250.0
    msgs = [r.message for r in caplog.records]
    assert any("checkpoint-restart" in m for m in msgs)


def test_rss_watchdog_disabled_by_zero_threshold():
    from twtml_tpu.utils.rss import RssWatchdog

    wd = RssWatchdog(warn_growth_mb=0.0, sample_every=1)
    for _ in range(5):
        wd.tick()
    assert wd.warn_count == 0
    assert wd.last_mb is not None
