"""Mesh-sharded ragged wire (VERDICT r3 #2): the shard-aligned ragged
layout (features/batch.align_ragged_shards + ops/ragged.ragged_repad) must
train BIT-IDENTICALLY to the single-device ragged wire — and the ragged
wire itself is already pinned bit-identical to the padded ground truth
(tests/test_ragged_wire.py), so equality here closes mesh == padded.

Covers: host re-layout roundtrip, the data-parallel mesh, the 2D
(data × model) feature-sharded mesh, the unaligned-single-device aliasing
(an aligned batch stepped WITHOUT a mesh), and the pinned unit bucket the
multi-host lockstep tick agrees on."""

import numpy as np
import pytest

import jax

from twtml_tpu.features.batch import (
    RAGGED_UNIT_MULTIPLE,
    RaggedUnitBatch,
    align_ragged_shards,
)
from twtml_tpu.features.featurizer import Featurizer
from twtml_tpu.models import StreamingLinearRegressionWithSGD
from twtml_tpu.parallel import ParallelSGDModel, make_mesh
from twtml_tpu.parallel.sharding import shard_batch
from twtml_tpu.streaming.sources import SyntheticSource


def synthetic(n=96, seed=13):
    return list(
        SyntheticSource(total=n, seed=seed, base_ms=1785320000000).produce()
    )


def ragged_chunks(statuses, rows=32, **feat_kw):
    feat = Featurizer(now_ms=1785320000000, **feat_kw)
    return [
        feat.featurize_batch_ragged(
            statuses[i : i + rows], row_bucket=rows, unit_bucket=64
        )
        for i in range(0, len(statuses), rows)
    ]


def test_align_roundtrip_repad_identical():
    """Alignment is a pure re-layout: the on-device re-pad of the aligned
    buffer equals the re-pad of the flat buffer, row for row."""
    from twtml_tpu.ops.ragged import ragged_repad

    for rb in ragged_chunks(synthetic()):
        flat_buf, flat_len = ragged_repad(
            rb.units, rb.offsets, rb.row_len, rb.mask.shape[0]
        )
        for s in (2, 4, 8):
            ab = align_ragged_shards(rb, s)
            assert ab.num_shards == s
            assert ab.units.shape[0] % s == 0
            a_buf, a_len = ragged_repad(
                ab.units, ab.offsets, ab.row_len, ab.mask.shape[0]
            )
            np.testing.assert_array_equal(np.asarray(a_buf), np.asarray(flat_buf))
            np.testing.assert_array_equal(np.asarray(a_len), np.asarray(flat_len))


def test_align_rejects_bad_shapes():
    rb = ragged_chunks(synthetic(n=32))[0]
    with pytest.raises(ValueError, match="not divisible"):
        align_ragged_shards(rb, 5)
    ab = align_ragged_shards(rb, 4)
    with pytest.raises(ValueError, match="already shard-aligned"):
        align_ragged_shards(ab, 8)
    with pytest.raises(ValueError, match="exceed the pinned bucket"):
        # every real row's units can't fit a 0-unit... use a tiny non-multiple
        align_ragged_shards(rb, 4, unit_bucket=1)


def test_pinned_unit_bucket_shapes():
    """The multi-host path pins the per-shard sub-buffer capacity so every
    process compiles one program; the pinned layout must still re-pad
    identically."""
    from twtml_tpu.ops.ragged import ragged_repad

    rb = ragged_chunks(synthetic(n=32))[0]
    ab = align_ragged_shards(rb, 2, unit_bucket=2 * RAGGED_UNIT_MULTIPLE)
    assert ab.units.shape == (2 * 2 * RAGGED_UNIT_MULTIPLE,)
    a_buf, _ = ragged_repad(ab.units, ab.offsets, ab.row_len, ab.mask.shape[0])
    f_buf, _ = ragged_repad(rb.units, rb.offsets, rb.row_len, rb.mask.shape[0])
    np.testing.assert_array_equal(np.asarray(a_buf), np.asarray(f_buf))


def test_prealigned_batch_grows_to_pinned_bucket():
    """The one-data-shard-per-process topology: a FLAT batch is trivially
    aligned to 1 shard, and the multi-host agreed bucket can exceed this
    host's buffer — align must PAD UP (tail zeros; segment-relative
    offsets untouched), not raise (r4 review finding)."""
    from twtml_tpu.ops.ragged import ragged_repad

    rb = ragged_chunks(synthetic(n=32))[0]
    assert rb.num_shards == 1 and rb.units.shape[0] == RAGGED_UNIT_MULTIPLE
    grown = align_ragged_shards(rb, 1, unit_bucket=2 * RAGGED_UNIT_MULTIPLE)
    assert grown.units.shape == (2 * RAGGED_UNIT_MULTIPLE,)
    np.testing.assert_array_equal(grown.offsets, rb.offsets)
    g_buf, _ = ragged_repad(
        grown.units, grown.offsets, grown.row_len, grown.mask.shape[0]
    )
    f_buf, _ = ragged_repad(rb.units, rb.offsets, rb.row_len, rb.mask.shape[0])
    np.testing.assert_array_equal(np.asarray(g_buf), np.asarray(f_buf))
    # shrinking below the current buffer is still an error
    with pytest.raises(ValueError, match="cannot\n?\\s*shrink"):
        align_ragged_shards(grown, 1, unit_bucket=RAGGED_UNIT_MULTIPLE)


def test_aligned_batch_single_device_matches_flat():
    """An aligned batch stepped WITHOUT a mesh (num_shards > 1, no axis)
    must train identically to the flat ragged batch — the segment-aware
    repad path."""
    chunks = ragged_chunks(synthetic())
    flat = StreamingLinearRegressionWithSGD(num_iterations=5)
    aligned = StreamingLinearRegressionWithSGD(num_iterations=5)
    for rb in chunks:
        out_f = flat.step(rb)
        out_a = aligned.step(align_ragged_shards(rb, 4))
        for a, b in zip(out_f, out_a):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(flat.latest_weights, aligned.latest_weights)


def padded_chunks(statuses, rows=32, **feat_kw):
    feat = Featurizer(now_ms=1785320000000, **feat_kw)
    return [
        feat.featurize_batch_units(
            statuses[i : i + rows], row_bucket=rows, unit_bucket=64
        )
        for i in range(0, len(statuses), rows)
    ]


def test_data_mesh_ragged_bit_matches_padded_mesh():
    """4-way data-parallel mesh: the ragged wire must train BIT-identically
    to the padded wire on the SAME mesh (same collectives; only the wire
    differs — the exact parity law every fast path carries). Plus a
    float-tolerance check against single-device (summation order differs
    across psum shards, as with the padded wire)."""
    statuses = synthetic()
    r_chunks = ragged_chunks(statuses)
    p_chunks = padded_chunks(statuses)
    mesh = make_mesh(num_data=4, devices=jax.devices()[:4])
    m_ragged = ParallelSGDModel(mesh, num_iterations=5, step_size=0.1)
    m_padded = ParallelSGDModel(mesh, num_iterations=5, step_size=0.1)
    single = StreamingLinearRegressionWithSGD(num_iterations=5, step_size=0.1)
    for rb, pb in zip(r_chunks, p_chunks):
        out_r = m_ragged.step(shard_batch(rb, mesh))
        out_p = m_padded.step(shard_batch(pb, mesh))
        single.step(rb)
        for a, b in zip(out_r, out_p):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(
        m_ragged.latest_weights, m_padded.latest_weights
    )
    np.testing.assert_allclose(
        m_ragged.latest_weights, single.latest_weights, rtol=1e-4, atol=1e-5
    )


def test_2d_mesh_ragged_bit_matches_padded_mesh():
    """The (data=2, model=4) feature-sharded mesh accepts the ragged wire
    and bit-matches the padded wire on the same mesh — the long-context
    layout no longer falls back to the padded wire (the r3 regression
    VERDICT #2 named)."""
    f_text = 512
    statuses = synthetic()
    r_chunks = ragged_chunks(statuses, num_text_features=f_text)
    p_chunks = padded_chunks(statuses, num_text_features=f_text)
    mesh = make_mesh(num_data=2, num_model=4)
    kw = dict(num_text_features=f_text, num_iterations=5, step_size=0.1)
    m_ragged = ParallelSGDModel(mesh, **kw)
    m_padded = ParallelSGDModel(mesh, **kw)
    for rb, pb in zip(r_chunks, p_chunks):
        out_r = m_ragged.step(shard_batch(rb, mesh))
        out_p = m_padded.step(shard_batch(pb, mesh))
        for a, b in zip(out_r, out_p):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(
        m_ragged.latest_weights, m_padded.latest_weights
    )


def test_shard_batch_reuses_prealigned():
    """shard_batch must not re-align an already-aligned batch (the
    featurizer/multi-host path aligns at build time)."""
    rb = ragged_chunks(synthetic(n=32))[0]
    mesh = make_mesh(num_data=4, devices=jax.devices()[:4])
    ab = align_ragged_shards(rb, 4)
    sb = shard_batch(ab, mesh)
    assert sb.num_shards == 4
    np.testing.assert_array_equal(np.asarray(sb.units), np.asarray(ab.units))


# -- degenerate shard segments (ISSUE 3 satellite) ---------------------------
# The lockstep all-padding-batch contract (streaming/context._lockstep_loop:
# dry shards dispatch all-padding batches every tick) means the sharded
# one-buffer wire MUST round-trip shards that hold no rows at all, and
# shards that hold exactly one tweet — the boundary cases of the
# segment-relative offset layout (and of its uint16-delta encoding).


def _sparse_ragged(n_real, rows=32, seed=21):
    """A ragged batch whose last shards are pure padding: only the first
    ``n_real`` rows are real (featurizer pads the rest)."""
    feat = Featurizer(now_ms=1785320000000)
    return feat.featurize_batch_ragged(
        synthetic(n=n_real, seed=seed), row_bucket=rows, unit_bucket=64,
        pre_filtered=True,
    )


@pytest.mark.parametrize("n_real", [3, 1, 32])
def test_degenerate_shards_roundtrip_one_buffer_wire(n_real):
    """All-padding shards (n_real=3 → shards 1-3 empty; n_real=1 → a
    single-tweet shard plus three empty ones) must round-trip the sharded
    one-buffer wire bit-identically — packed sharded AND coalesced group,
    narrow and int32 offsets."""
    from twtml_tpu.features.batch import (
        pack_ragged_group,
        pack_ragged_sharded,
        unpack_batch,
    )

    rb = _sparse_ragged(n_real)
    aligned = align_ragged_shards(rb, 4)
    assert rb.num_valid == n_real
    for narrow in (None, False):
        pk = pack_ragged_sharded(aligned, narrow_offsets=narrow)
        back = unpack_batch(pk.buffer, pk.layout)
        for f in ("units", "offsets", "numeric", "label", "mask"):
            np.testing.assert_array_equal(
                np.asarray(getattr(back, f)), np.asarray(getattr(aligned, f))
            )
        assert back.num_shards == 4
        pg = pack_ragged_group([aligned], narrow_offsets=narrow)
        gback = unpack_batch(pg.buffer, pg.layout)
        for f in ("units", "offsets", "numeric", "label", "mask"):
            np.testing.assert_array_equal(
                np.asarray(getattr(gback, f))[0],
                np.asarray(getattr(aligned, f)),
            )


@pytest.mark.parametrize("n_real", [3, 1])
def test_degenerate_shards_train_identically_on_mesh(n_real):
    """The mesh step over the one-buffer wire with empty/single-tweet
    shards equals the flat single-device ragged step — the app-level form
    of the lockstep all-padding contract."""
    rb = _sparse_ragged(n_real)
    ref = StreamingLinearRegressionWithSGD(num_iterations=5, step_size=0.05)
    out_ref = ref.step(rb)

    mesh = make_mesh(num_data=4, devices=jax.devices()[:4])
    m = ParallelSGDModel(mesh, num_iterations=5, step_size=0.05)
    out_pk = m.step(m.pack_for_wire(rb))
    assert float(out_pk.count) == float(out_ref.count) == n_real
    np.testing.assert_array_equal(
        np.asarray(out_pk.predictions), np.asarray(out_ref.predictions)
    )
    np.testing.assert_array_equal(m.latest_weights, ref.latest_weights)

    g = ParallelSGDModel(mesh, num_iterations=5, step_size=0.05)
    many = g.step_many(g.pack_group_for_wire([rb]))
    assert float(many.count[0]) == n_real
    np.testing.assert_array_equal(g.latest_weights, ref.latest_weights)
