"""Multi-host integration: a REAL two-process jax.distributed group.

The reference's multi-node story is Spark cluster managers (README.md:40-55);
ours is jax.distributed + mesh collectives (parallel/distributed.py). The
other parallel tests exercise the program structure on a single-process
virtual mesh; this one actually forms a two-process group over localhost
(gloo CPU collectives, 2 virtual devices per process = 4 global), shards the
stream by host, assembles the global batch with host_local_batch_to_global,
and checks both processes train in lockstep — and match a single-process run
over the same tweets, for both wire formats (host-hashed tokens and raw
code units).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_group(
    wire: str, nprocs: int = 2, timeout: float = 180.0, mesh: str = "1d",
    extra_env: dict | None = None,
):
    port = _free_port()
    env = dict(os.environ, PYTHONPATH=REPO, **(extra_env or {}))
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), str(nprocs), str(port), wire, mesh],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for i in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=timeout)
            if p.returncode != 0:
                pytest.fail(f"worker failed rc={p.returncode}:\n{stderr[-2000:]}")
            outs.append(json.loads(stdout.strip().splitlines()[-1]))
    finally:
        for p in procs:
            p.kill()
    return outs


def _single_process_expectation(wire: str):
    """The same 64 tweets, host-sharded the same way, in one process."""
    from twtml_tpu.features.batch import FeatureBatch, UnitBatch
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource

    statuses = list(
        SyntheticSource(total=64, seed=7, base_ms=1785320000000).produce()
    )
    feat = Featurizer(now_ms=1785320000000)
    shards = []
    for pid in range(2):
        local = statuses[pid::2]
        if wire == "unit":
            shards.append(feat.featurize_batch_units(
                local, row_bucket=16, unit_bucket=64, pre_filtered=True
            ))
        else:
            shards.append(feat.featurize_batch(
                local, row_bucket=16, token_bucket=64, pre_filtered=True
            ))
    cls = UnitBatch if wire == "unit" else FeatureBatch
    global_batch = cls(*(
        np.concatenate([getattr(s, f) for s in shards], axis=0)
        for f in cls._fields
    ))
    model = StreamingLinearRegressionWithSGD(num_iterations=5, step_size=0.005)
    out = model.step(global_batch)
    return float(out.count), float(out.mse), model.latest_weights


@pytest.mark.parametrize("wire", ["host", "unit"])
def test_two_process_group_trains_in_lockstep(wire):
    outs = _run_group(wire)
    assert [o["process"] for o in sorted(outs, key=lambda o: o["process"])] == [0, 1]
    # both processes observe identical global stats and weights
    assert outs[0]["count"] == outs[1]["count"] == 64.0
    assert outs[0]["mse"] == pytest.approx(outs[1]["mse"], rel=1e-6)
    np.testing.assert_allclose(outs[0]["weights"], outs[1]["weights"], rtol=1e-6)
    # and they match the single-process ground truth over the same tweets
    count, mse, weights = _single_process_expectation(wire)
    assert outs[0]["count"] == count
    assert outs[0]["mse"] == pytest.approx(mse, rel=1e-4)
    np.testing.assert_allclose(
        outs[0]["weights"], weights, rtol=1e-4, atol=1e-7
    )


def test_two_process_2d_mesh_checkpoint_roundtrip(tmp_path):
    """Checkpoint round-trip where weight shards span PROCESS boundaries:
    latest_weights process_allgathers, pid 0 writes, both restore into fresh
    models whose text shards are not fully addressable, training continues —
    equal to an uninterrupted 2-step single-process run."""
    outs = _run_group(
        "unit", mesh="2d_ckpt", extra_env={"TWTML_CKPT_DIR": str(tmp_path)}
    )
    assert outs[0]["count"] == outs[1]["count"] == 64.0
    np.testing.assert_allclose(outs[0]["weights"], outs[1]["weights"], rtol=1e-6)

    # single-process ground truth: the same two steps, no interruption
    from twtml_tpu.models import StreamingLinearRegressionWithSGD

    from twtml_tpu.features.batch import UnitBatch
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.streaming.sources import SyntheticSource

    statuses = list(
        SyntheticSource(total=64, seed=7, base_ms=1785320000000).produce()
    )
    feat = Featurizer(now_ms=1785320000000)
    shards = [
        feat.featurize_batch_units(
            statuses[pid::2], row_bucket=16, unit_bucket=64, pre_filtered=True
        )
        for pid in range(2)
    ]
    global_batch = UnitBatch(*(
        np.concatenate([getattr(s, f) for s in shards], axis=0)
        for f in UnitBatch._fields
    ))
    model = StreamingLinearRegressionWithSGD(num_iterations=5, step_size=0.005)
    model.step(global_batch)
    model.step(global_batch)
    np.testing.assert_allclose(
        outs[0]["weights"], model.latest_weights, rtol=1e-4, atol=1e-7
    )


def test_two_process_2d_mesh_feature_sharding():
    """(data=2, model=2) mesh across TWO processes with the model axis
    deliberately pairing devices from DIFFERENT processes: the per-iteration
    feature-shard psum crosses the process boundary (the DCN-analog path),
    each weight shard is not fully addressable from one process (the
    latest_weights allgather), and the result still matches the
    single-process ground truth."""
    outs = _run_group("unit", mesh="2d")
    assert outs[0]["count"] == outs[1]["count"] == 64.0
    np.testing.assert_allclose(outs[0]["weights"], outs[1]["weights"], rtol=1e-6)
    count, mse, weights = _single_process_expectation("unit")
    assert outs[0]["mse"] == pytest.approx(mse, rel=1e-4)
    np.testing.assert_allclose(outs[0]["weights"], weights, rtol=1e-4, atol=1e-7)


def test_two_process_2d_mesh_gram_inner_loop():
    """The Gram (dual) inner loop with both of its per-batch collectives
    crossing REAL process boundaries — the batch all-gather over 'data' and
    the G row-panel psum over 'model' (models/sgd.py run_dual_loop,
    parallel/sharding.py) — still matches the single-process dense math."""
    outs = _run_group("unit", mesh="2d_gram")
    assert outs[0]["count"] == outs[1]["count"] == 64.0
    np.testing.assert_allclose(outs[0]["weights"], outs[1]["weights"], rtol=1e-6)
    _, mse, weights = _single_process_expectation("unit")
    assert outs[0]["mse"] == pytest.approx(mse, rel=1e-4)
    np.testing.assert_allclose(outs[0]["weights"], weights, rtol=1e-4, atol=1e-6)
