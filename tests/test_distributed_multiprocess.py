"""Multi-host integration: a REAL two-process jax.distributed group.

The reference's multi-node story is Spark cluster managers (README.md:40-55);
ours is jax.distributed + mesh collectives (parallel/distributed.py). The
other parallel tests exercise the program structure on a single-process
virtual mesh; this one actually forms a two-process group over localhost
(gloo CPU collectives, 2 virtual devices per process = 4 global), shards the
stream by host, assembles the global batch with host_local_batch_to_global,
and checks both processes train in lockstep — and match a single-process run
over the same tweets, for both wire formats (host-hashed tokens and raw
code units).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "distributed_worker.py")
APP_WORKER = os.path.join(REPO, "tests", "app_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_group(
    wire: str, nprocs: int = 2, timeout: float = 180.0, mesh: str = "1d",
    extra_env: dict | None = None,
):
    port = _free_port()
    env = dict(os.environ, PYTHONPATH=REPO, **(extra_env or {}))
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), str(nprocs), str(port), wire, mesh],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for i in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=timeout)
            if p.returncode != 0:
                pytest.fail(f"worker failed rc={p.returncode}:\n{stderr[-2000:]}")
            outs.append(json.loads(stdout.strip().splitlines()[-1]))
    finally:
        for p in procs:
            p.kill()
    return outs


def _single_process_expectation(wire: str):
    """The same 64 tweets, host-sharded the same way, in one process."""
    from twtml_tpu.features.batch import FeatureBatch, UnitBatch
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource

    statuses = list(
        SyntheticSource(total=64, seed=7, base_ms=1785320000000).produce()
    )
    feat = Featurizer(now_ms=1785320000000)
    shards = []
    for pid in range(2):
        local = statuses[pid::2]
        if wire == "unit":
            shards.append(feat.featurize_batch_units(
                local, row_bucket=16, unit_bucket=64, pre_filtered=True
            ))
        else:
            shards.append(feat.featurize_batch(
                local, row_bucket=16, token_bucket=64, pre_filtered=True
            ))
    cls = UnitBatch if wire == "unit" else FeatureBatch
    global_batch = cls(*(
        np.concatenate([getattr(s, f) for s in shards], axis=0)
        for f in cls._fields
    ))
    model = StreamingLinearRegressionWithSGD(num_iterations=5, step_size=0.005)
    out = model.step(global_batch)
    return float(out.count), float(out.mse), model.latest_weights


@pytest.mark.parametrize("wire", ["host", "unit"])
def test_two_process_group_trains_in_lockstep(wire):
    outs = _run_group(wire)
    assert [o["process"] for o in sorted(outs, key=lambda o: o["process"])] == [0, 1]
    # both processes observe identical global stats and weights
    assert outs[0]["count"] == outs[1]["count"] == 64.0
    assert outs[0]["mse"] == pytest.approx(outs[1]["mse"], rel=1e-6)
    np.testing.assert_allclose(outs[0]["weights"], outs[1]["weights"], rtol=1e-6)
    # and they match the single-process ground truth over the same tweets
    count, mse, weights = _single_process_expectation(wire)
    assert outs[0]["count"] == count
    assert outs[0]["mse"] == pytest.approx(mse, rel=1e-4)
    np.testing.assert_allclose(
        outs[0]["weights"], weights, rtol=1e-4, atol=1e-7
    )


def _run_app_group(app_args: list, nprocs: int, ndev: int, timeout=300.0,
                   extra_env: dict | None = None):
    """Drive a real entry-point main() in ``nprocs`` processes via
    tests/app_worker.py; returns each process's stdout."""
    port = _free_port()
    env = dict(os.environ, PYTHONPATH=REPO, **(extra_env or {}))
    procs = [
        subprocess.Popen(
            [sys.executable, APP_WORKER, str(i), str(nprocs), str(port),
             str(ndev)] + app_args,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for i in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=timeout)
            if p.returncode != 0:
                pytest.fail(
                    f"app worker failed rc={p.returncode}:\n{stderr[-3000:]}"
                )
            outs.append(stdout)
    finally:
        for p in procs:
            p.kill()
    return outs


def test_app_level_multihost_cli_trains_in_lockstep(tmp_path):
    """VERDICT r2 #1 done-criterion: two processes running the REAL
    linear-regression main with ``--master twtml://host:port`` (=
    --coordinator/--numProcesses/--processId) train in lockstep — same
    batch boundaries, same global per-batch stats (±1 on the rounded ints),
    and final weights matching a single-process run of the same app over
    the same replay file on the same total device count."""
    import json as _json

    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    path = tmp_path / "tweets.jsonl"
    statuses = list(
        SyntheticSource(total=200, seed=5, base_ms=1785320000000).produce()
    )
    with open(path, "w") as fh:
        for s in statuses:
            fh.write(_json.dumps(_status_json(s)) + "\n")

    closed = "http://127.0.0.1:9"  # closed port: telemetry Try paths, no DNS
    common = [
        "linear", "--source", "replay", "--replayFile", str(path),
        "--seconds", "0", "--backend", "cpu", "--tokenBucket", "64",
        "--lightning", closed, "--twtweb", closed,
    ]
    d_single, d_multi = str(tmp_path / "ck1"), str(tmp_path / "ck2")
    single = _run_app_group(
        common + ["--batchBucket", "32", "--checkpointDir", d_single],
        nprocs=1, ndev=4,
    )
    multi = _run_app_group(
        common + ["--batchBucket", "16", "--checkpointDir", d_multi],
        nprocs=2, ndev=2,
    )

    def stat_lines(out):
        return [ln for ln in out.splitlines() if ln.startswith("count:")]

    import re

    lead, follower = stat_lines(multi[0]), stat_lines(multi[1])
    ref = stat_lines(single[0])
    assert follower == []  # one telemetry owner per run
    assert len(lead) == len(ref) >= 5  # same batch boundaries incl. tail

    for got, want in zip(lead, ref):
        g = [int(x) for x in re.findall(r"-?\d+", got)]
        w = [int(x) for x in re.findall(r"-?\d+", want)]
        assert g[:2] == w[:2]  # cumulative count and batch size: exact
        for a, b in zip(g[2:], w[2:]):  # mse/stdevs: rounded ints, FP order
            assert abs(a - b) <= 2, (got, want)

    from twtml_tpu.checkpoint import Checkpointer

    w_single, meta_s = Checkpointer(d_single).restore()
    w_multi, meta_m = Checkpointer(d_multi).restore()
    assert meta_s["count"] == meta_m["count"] == 200
    assert meta_s["batches"] == meta_m["batches"] == len(ref)
    np.testing.assert_allclose(w_multi, w_single, rtol=1e-4, atol=1e-7)

    # resume: a second multi-host run on the same dir is an r21 EXACT
    # resume — every host restores the lead's broadcast checkpoint and
    # fast-forwards past its own journaled shard (the corpus is fully
    # covered), so nothing retrains and the counters are unchanged
    multi2 = _run_app_group(
        common + ["--batchBucket", "16", "--checkpointDir", d_multi],
        nprocs=2, ndev=2,
    )
    assert stat_lines(multi2[0]) == []  # no new batches: exactly-once
    _, meta_m2 = Checkpointer(d_multi).restore()
    assert meta_m2["count"] == 200

    # --journal off restores the pre-r21 resume semantics: the corpus
    # re-trains on top of the restored counters on every host
    multi3 = _run_app_group(
        common + ["--batchBucket", "16", "--checkpointDir", d_multi,
                  "--journal", "off"],
        nprocs=2, ndev=2,
    )
    lead3 = stat_lines(multi3[0])
    assert lead3, "journal-off resume produced no batches"
    first = [int(x) for x in re.findall(r"-?\d+", lead3[0])]
    assert first[0] == 200 + first[1]  # cumulative count resumed from 200
    _, meta_m3 = Checkpointer(d_multi).restore()
    assert meta_m3["count"] == 400


def test_app_level_multihost_ragged_wire(tmp_path):
    """r4 (VERDICT r3 #2): the RAGGED wire through the real multi-host CLI —
    each host re-lays its rows into shard-aligned segments with the
    per-shard bucket agreed by allgather (parallel/distributed.py), and the
    run matches a single-process MESH run of the same app with the same
    wire (which itself bit-matches the padded wire,
    tests/test_ragged_sharded.py)."""
    import json as _json
    import re

    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    path = tmp_path / "tweets.jsonl"
    statuses = list(
        SyntheticSource(total=128, seed=9, base_ms=1785320000000).produce()
    )
    with open(path, "w") as fh:
        for s in statuses:
            fh.write(_json.dumps(_status_json(s)) + "\n")

    closed = "http://127.0.0.1:9"
    common = [
        "linear", "--source", "replay", "--replayFile", str(path),
        "--seconds", "0", "--backend", "cpu", "--tokenBucket", "64",
        "--wire", "ragged", "--hashOn", "device",
        "--lightning", closed, "--twtweb", closed,
    ]
    d_single, d_multi = str(tmp_path / "ck1"), str(tmp_path / "ck2")
    single = _run_app_group(
        common + ["--batchBucket", "32", "--checkpointDir", d_single],
        nprocs=1, ndev=4,
    )
    multi = _run_app_group(
        common + ["--batchBucket", "16", "--checkpointDir", d_multi],
        nprocs=2, ndev=2,
    )

    def stat_lines(out):
        return [ln for ln in out.splitlines() if ln.startswith("count:")]

    lead, follower = stat_lines(multi[0]), stat_lines(multi[1])
    ref = stat_lines(single[0])
    assert follower == []  # one telemetry owner per run
    assert len(lead) == len(ref) >= 3

    for got, want in zip(lead, ref):
        g = [int(x) for x in re.findall(r"-?\d+", got)]
        w = [int(x) for x in re.findall(r"-?\d+", want)]
        assert g[:2] == w[:2]  # cumulative count and batch size: exact
        for a, b in zip(g[2:], w[2:]):  # mse/stdevs: rounded ints, FP order
            assert abs(a - b) <= 2, (got, want)

    from twtml_tpu.checkpoint import Checkpointer

    w_single, meta_s = Checkpointer(d_single).restore()
    w_multi, meta_m = Checkpointer(d_multi).restore()
    assert meta_s["count"] == meta_m["count"] == 128
    np.testing.assert_allclose(w_multi, w_single, rtol=1e-4, atol=1e-7)

    # the one-data-shard-per-process topology (local_shards == 1): a flat
    # batch is trivially "aligned" and hosts' buffers can differ — the
    # agreed bucket must grow the smaller host, never raise (r4 review)
    d_one = str(tmp_path / "ck3")
    one = _run_app_group(
        common + ["--batchBucket", "16", "--checkpointDir", d_one],
        nprocs=2, ndev=1,
    )
    lead1 = stat_lines(one[0])
    assert stat_lines(one[1]) == []
    assert len(lead1) == len(ref)
    w_one, meta_o = Checkpointer(d_one).restore()
    assert meta_o["count"] == 128
    np.testing.assert_allclose(w_one, w_single, rtol=1e-4, atol=1e-7)


def test_app_level_multihost_kmeans_lockstep(tmp_path):
    """The k-means entry through the multi-host CLI: per-host sharded
    intake, GLOBAL per-batch StandardScaler, mesh psums spanning hosts —
    lead-printed centers/counts match a single-process run of the same app
    over the same replay file (same global batch rows, interleaved
    order)."""
    import json as _json
    import re

    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    path = tmp_path / "tweets.jsonl"
    statuses = list(
        SyntheticSource(total=96, seed=6, base_ms=1785320000000).produce()
    )
    with open(path, "w") as fh:
        for s in statuses:
            fh.write(_json.dumps(_status_json(s)) + "\n")

    closed = "http://127.0.0.1:9"
    common = [
        "kmeans", "--source", "replay", "--replayFile", str(path),
        "--seconds", "0", "--backend", "cpu",
        "--lightning", closed, "--twtweb", closed,
    ]
    single = _run_app_group(common + ["--batchBucket", "32"], nprocs=1, ndev=4)
    multi = _run_app_group(common + ["--batchBucket", "16"], nprocs=2, ndev=2)

    def stat_lines(out):
        return [ln for ln in out.splitlines() if ln.startswith("count:")]

    lead, follower = stat_lines(multi[0]), stat_lines(multi[1])
    ref = stat_lines(single[0])
    assert follower == []
    assert len(lead) == len(ref) >= 2
    for got, want in zip(lead, ref):
        g = [float(x) for x in re.findall(r"-?\d+\.?\d*", got)]
        w = [float(x) for x in re.findall(r"-?\d+\.?\d*", want)]
        assert g[:2] == w[:2]  # cumulative count and batch size: exact
        # centers (rounded to 3 decimals) agree within FP-order noise of
        # the interleaved global row order
        assert len(g) == len(w)
        for a, b in zip(g[2:], w[2:]):
            assert abs(a - b) <= max(0.02, 0.02 * abs(b)), (got, want)


def test_two_process_2d_mesh_checkpoint_roundtrip(tmp_path):
    """Checkpoint round-trip where weight shards span PROCESS boundaries:
    latest_weights process_allgathers, pid 0 writes, both restore into fresh
    models whose text shards are not fully addressable, training continues —
    equal to an uninterrupted 2-step single-process run."""
    outs = _run_group(
        "unit", mesh="2d_ckpt", extra_env={"TWTML_CKPT_DIR": str(tmp_path)}
    )
    assert outs[0]["count"] == outs[1]["count"] == 64.0
    np.testing.assert_allclose(outs[0]["weights"], outs[1]["weights"], rtol=1e-6)

    # single-process ground truth: the same two steps, no interruption
    from twtml_tpu.models import StreamingLinearRegressionWithSGD

    from twtml_tpu.features.batch import UnitBatch
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.streaming.sources import SyntheticSource

    statuses = list(
        SyntheticSource(total=64, seed=7, base_ms=1785320000000).produce()
    )
    feat = Featurizer(now_ms=1785320000000)
    shards = [
        feat.featurize_batch_units(
            statuses[pid::2], row_bucket=16, unit_bucket=64, pre_filtered=True
        )
        for pid in range(2)
    ]
    global_batch = UnitBatch(*(
        np.concatenate([getattr(s, f) for s in shards], axis=0)
        for f in UnitBatch._fields
    ))
    model = StreamingLinearRegressionWithSGD(num_iterations=5, step_size=0.005)
    model.step(global_batch)
    model.step(global_batch)
    np.testing.assert_allclose(
        outs[0]["weights"], model.latest_weights, rtol=1e-4, atol=1e-7
    )


def test_two_process_2d_mesh_feature_sharding():
    """(data=2, model=2) mesh across TWO processes with the model axis
    deliberately pairing devices from DIFFERENT processes: the per-iteration
    feature-shard psum crosses the process boundary (the DCN-analog path),
    each weight shard is not fully addressable from one process (the
    latest_weights allgather), and the result still matches the
    single-process ground truth."""
    outs = _run_group("unit", mesh="2d")
    assert outs[0]["count"] == outs[1]["count"] == 64.0
    np.testing.assert_allclose(outs[0]["weights"], outs[1]["weights"], rtol=1e-6)
    count, mse, weights = _single_process_expectation("unit")
    assert outs[0]["mse"] == pytest.approx(mse, rel=1e-4)
    np.testing.assert_allclose(outs[0]["weights"], weights, rtol=1e-4, atol=1e-7)


def test_two_process_2d_mesh_gram_inner_loop():
    """The Gram (dual) inner loop with both of its per-batch collectives
    crossing REAL process boundaries — the batch all-gather over 'data' and
    the G row-panel psum over 'model' (models/sgd.py run_dual_loop,
    parallel/sharding.py) — still matches the single-process dense math."""
    outs = _run_group("unit", mesh="2d_gram")
    assert outs[0]["count"] == outs[1]["count"] == 64.0
    np.testing.assert_allclose(outs[0]["weights"], outs[1]["weights"], rtol=1e-6)
    _, mse, weights = _single_process_expectation("unit")
    assert outs[0]["mse"] == pytest.approx(mse, rel=1e-4)
    np.testing.assert_allclose(outs[0]["weights"], weights, rtol=1e-4, atol=1e-6)


def test_two_process_tenants_on_cross_process_model_axis():
    """ISSUE 7: the multi-tenant plane with the TENANT axis on the
    cross-process MODEL axis — each process holds half the tenants' weight
    shards (not fully addressable → the latest_weights allgather runs),
    rows shard over 'data', and no collective crosses the tenant axis.
    Both processes must agree exactly with each other AND match a
    single-process tenant stack over the same stream."""
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.parallel import TenantStackModel
    from twtml_tpu.streaming.sources import SyntheticSource

    outs = _run_group("unit", mesh="tenants")
    assert outs[0]["weights_addressable"] is False
    # cross-host agreement is exact: same program, same placement
    assert outs[0]["tenant_counts"] == outs[1]["tenant_counts"]
    assert outs[0]["tenant_mses"] == outs[1]["tenant_mses"]
    np.testing.assert_array_equal(outs[0]["weights"], outs[1]["weights"])

    statuses = list(
        SyntheticSource(total=64, seed=7, base_ms=1785320000000).produce()
    )
    feat = Featurizer(now_ms=1785320000000)
    ref = TenantStackModel(4, num_iterations=5, step_size=0.005)
    for sts in (statuses[:32], statuses[32:]):
        out = ref.step(feat.featurize_batch_units(
            sts, row_bucket=32, unit_bucket=64, pre_filtered=True
        ))
    assert outs[0]["tenant_counts"] == np.asarray(out.count).tolist()
    np.testing.assert_allclose(
        outs[0]["tenant_mses"], np.asarray(out.mse).tolist(), rtol=1e-5
    )
    np.testing.assert_allclose(
        outs[0]["weights"], ref.latest_weights, rtol=1e-4, atol=1e-7
    )


def test_app_level_multihost_sentinel_rollback(tmp_path):
    """r7 (ISSUE 4): the divergence sentinel on a REAL two-process group.
    Each host's --chaos source.nan@2 poisons its local rows of the SAME
    global batch (per-host injectors, identical tick counters), both hosts
    see the same non-finite psum stats at the same deterministic delivery,
    and both roll back the same step: the lead restores its verified
    checkpoint from disk and BROADCASTS it (the follower has no checkpoint
    files), the rollback count rides the cadence allgather with no
    disagreement abort, the poisoned batch is skipped, and the run
    completes cleanly."""
    import json as _json

    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    path = tmp_path / "tweets.jsonl"
    statuses = list(
        SyntheticSource(total=96, seed=33, base_ms=1785320000000).produce()
    )
    with open(path, "w") as fh:
        for s in statuses:
            fh.write(_json.dumps(_status_json(s)) + "\n")

    closed = "http://127.0.0.1:9"
    d_ck = str(tmp_path / "ck")
    port = _free_port()
    env = dict(os.environ, PYTHONPATH=REPO)
    procs = [
        subprocess.Popen(
            [sys.executable, APP_WORKER, str(i), "2", str(port), "2",
             "linear", "--source", "replay", "--replayFile", str(path),
             "--seconds", "0", "--backend", "cpu",
             "--batchBucket", "16", "--tokenBucket", "64",
             "--checkpointDir", d_ck, "--checkpointEvery", "1",
             "--chaos", "source.nan@2",
             "--lightning", closed, "--twtweb", closed],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs, errs = [], []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=300.0)
            if p.returncode != 0:
                pytest.fail(
                    f"worker failed rc={p.returncode}:\n{stderr[-3000:]}"
                )
            outs.append(stdout)
            errs.append(stderr)
    finally:
        for p in procs:
            p.kill()

    # BOTH hosts rolled back (lead from disk, follower via the broadcast)
    # — and the allgather-ridden counts never disagreed
    for err in errs:
        assert "rolled back to verified checkpoint" in err, err[-2000:]
        assert "disagree on sentinel rollback counts" not in err

    lead = [ln for ln in outs[0].splitlines() if ln.startswith("count:")]
    follower = [ln for ln in outs[1].splitlines() if ln.startswith("count:")]
    assert follower == []  # one telemetry owner per run
    # 3 global batches of 32; the sentinel skips the poisoned 2nd, and the
    # r21 intake journal (auto-on with --checkpointDir) replays its rows
    # on BOTH hosts — the journal seam sits upstream of the poison
    # injection point, so they re-featurize clean and all 3 batches train
    assert len(lead) == 3
    assert "count: 96" in lead[-1]
    for err in errs:
        assert "journal: replayed" in err, err[-2000:]

    from twtml_tpu.checkpoint import Checkpointer

    state, meta = Checkpointer(d_ck).restore()
    assert meta["count"] == 96
    assert meta["batches"] == 3
    assert np.isfinite(np.asarray(state)).all()


def test_sideband_straggler_names_delayed_host_with_no_extra_collectives():
    """ISSUE 5 acceptance: a REAL two-process lockstep run with host 1
    artificially delayed via --chaos (a step:delay stall inside the
    dispatch window). The per-host sideband rides the one cadence
    allgather — asserted by COUNTING the allgathers (exactly one per
    lockstep tick: the cadence count is unchanged by the sideband) and the
    jax.device_get calls (one per dispatched batch: zero added host
    fetches) — and BOTH hosts' straggler attributors must name host 1,
    attributed to the upload (dispatch) rung of the bottleneck ladder."""
    outs = _run_group("unit", mesh="sideband", timeout=240.0)
    by_pid = {o["process"]: o for o in outs}
    for pid in (0, 1):
        o = by_pid[pid]
        assert o["terminated"] and not o["failed"]
        assert o["batches"] >= 6
        # zero added collectives: the cadence allgather count IS the tick
        # count — the sideband widened the payload, never the call count
        assert o["allgathers"] == o["ticks"], o
        # zero added host fetches: one pooled device_get per dispatched
        # batch (the FetchPipeline contract), none from the sideband
        assert o["device_gets"] == o["batches"] == o["fetch_count"], o
        # every host sees the whole fleet and the same verdict
        assert o["num_hosts_seen"] == 2
        assert o["straggler_host"] == 1, o
        assert o["view_straggler"] == 1
        assert o["view_stage"] == "upload", o
        assert o["tick_skew_ms"] > 50.0, o


def test_lockstep_abort_propagates_instead_of_hanging():
    """A batch failure on one host aborts the GROUP: the failing host
    broadcasts abort on its next tick, the healthy peer stops instead of
    stalling in its next collective, and both mark the run failed."""
    outs = _run_group("unit", mesh="lockstep_abort", timeout=120.0)
    by_pid = {o["process"]: o for o in outs}
    assert by_pid[0]["terminated"] and by_pid[1]["terminated"]
    assert by_pid[0]["failed"] and by_pid[1]["failed"]
    assert by_pid[1]["batches_seen"] == 3  # raised on its third batch


def test_lockstep_peer_death_watchdog_aborts_survivor():
    """A HARD-killed peer (os._exit mid-run: no abort broadcast, no
    goodbye) must not leave the survivor hanging forever in its next
    cadence allgather: the lockstep peer watchdog
    (TWTML_LOCKSTEP_TIMEOUT_S) — or the transport error a dead gloo peer
    raises — turns it into a loud failed abort within the timeout."""
    port = _free_port()
    env = dict(
        os.environ, PYTHONPATH=REPO, TWTML_LOCKSTEP_TIMEOUT_S="5",
    )
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), "2", str(port), "unit",
             "peer_kill"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for i in range(2)
    ]
    try:
        out0, err0 = procs[0].communicate(timeout=120.0)
        out1, _ = procs[1].communicate(timeout=120.0)
    finally:
        for p in procs:
            p.kill()
    assert procs[1].returncode == 42  # the hard kill
    assert out1.strip() == ""  # it never got to print
    assert procs[0].returncode == 0, f"survivor crashed:\n{err0[-3000:]}"
    res = json.loads(out0.strip().splitlines()[-1])
    assert res["terminated"], "survivor never left the lockstep loop"
    assert res["failed"], "survivor did not mark the run failed"
    assert res["batches_seen"] >= 3  # it trained up to the kill point


def test_app_level_multihost_wall_clock_intervals(tmp_path):
    """The lockstep scheduler's WALL-CLOCK branch (--seconds > 0): hosts
    tick on their own clocks, the per-tick allgather aligns them, and the
    run completes with all rows trained and one telemetry owner."""
    import json as _json

    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    path = tmp_path / "tweets.jsonl"
    with open(path, "w") as fh:
        for s in SyntheticSource(total=64, seed=8, base_ms=1785320000000).produce():
            fh.write(_json.dumps(_status_json(s)) + "\n")

    closed = "http://127.0.0.1:9"
    multi = _run_app_group([
        "linear", "--source", "replay", "--replayFile", str(path),
        "--seconds", "1", "--backend", "cpu",
        "--batchBucket", "16", "--tokenBucket", "64",
        "--lightning", closed, "--twtweb", closed,
    ], nprocs=2, ndev=2)

    lead = [ln for ln in multi[0].splitlines() if ln.startswith("count:")]
    follower = [ln for ln in multi[1].splitlines() if ln.startswith("count:")]
    assert follower == []
    assert lead, "no stats lines from the lead"
    assert "count: 64" in lead[-1]  # every row trained, wall-clock cadence


def test_app_level_multihost_block_ingest(tmp_path):
    """r5 (VERDICT r4 #4): --ingest block on a two-process group — each
    host parses only its BYTE-RANGE shard of the replay file
    (BlockReplayFileSource shard_index/count), lockstep drains split
    blocks to exactly the pinned bucket, and the run matches an in-process
    ground truth that emulates the same per-host intake (concatenated
    per-host buckets per tick through one single-device model)."""
    import json as _json

    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    path = tmp_path / "tweets.jsonl"
    statuses = list(
        SyntheticSource(total=200, seed=21, base_ms=1785320000000).produce()
    )
    with open(path, "w") as fh:
        for s in statuses:
            fh.write(_json.dumps(_status_json(s)) + "\n")

    closed = "http://127.0.0.1:9"
    d_multi = str(tmp_path / "ck")
    multi = _run_app_group([
        "linear", "--source", "replay", "--replayFile", str(path),
        "--ingest", "block", "--seconds", "0", "--backend", "cpu",
        "--batchBucket", "16", "--tokenBucket", "64",
        "--checkpointDir", d_multi,
        "--lightning", closed, "--twtweb", closed,
    ], nprocs=2, ndev=1,
        # pin the age-feature clock so the in-process ground truth below
        # (same fixed clock) is comparable bit-for-bit in features
        extra_env={"TWTML_NOW_MS": "1785320000000"})

    lead = [ln for ln in multi[0].splitlines() if ln.startswith("count:")]
    follower = [ln for ln in multi[1].splitlines() if ln.startswith("count:")]
    assert follower == []
    assert lead, "no stats lines from the lead"

    # in-process ground truth: the same byte-range shards, the same
    # 16-row buckets per tick, concatenated host0+host1 into the global
    # batch, through one single-device model
    from twtml_tpu.features.batch import UnitBatch
    from twtml_tpu.features.blocks import iter_row_chunks, empty_block
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import BlockReplayFileSource

    feat = Featurizer(now_ms=1785320000000)
    chunks = [
        list(iter_row_chunks(
            BlockReplayFileSource(
                str(path), shard_index=i, shard_count=2
            ).produce(), 16,
        ))
        for i in range(2)
    ]
    ticks = max(len(c) for c in chunks)
    # conf defaults (reference.conf): numIterations 50, stepSize 0.005
    model = StreamingLinearRegressionWithSGD(num_iterations=50, step_size=0.005)
    total = 0
    for k in range(ticks):
        host_batches = [
            feat.featurize_parsed_block(
                c[k] if k < len(c) else empty_block(),
                row_bucket=16, unit_bucket=64,
            )
            for c in chunks
        ]
        global_batch = UnitBatch(*(
            np.concatenate([getattr(b, f) for b in host_batches], axis=0)
            for f in UnitBatch._fields
        ))
        out = model.step(global_batch)
        total += int(out.count)
    assert total == 200

    from twtml_tpu.checkpoint import Checkpointer

    w_multi, meta = Checkpointer(d_multi).restore()
    assert meta["count"] == 200
    assert len(lead) == ticks
    np.testing.assert_allclose(
        w_multi, model.latest_weights, rtol=1e-4, atol=1e-7
    )


def test_app_level_multihost_superbatch(tmp_path):
    """r5 (VERDICT r4 #1c): --superBatch on a multi-host group — K-batch
    groups assemble as one global stacked dispatch on the lockstep tick,
    and the run is stats-identical to the same two-process run without the
    flag (the superbatch is semantics-invisible on every layout)."""
    import json as _json

    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    path = tmp_path / "tweets.jsonl"
    statuses = list(
        SyntheticSource(total=160, seed=23, base_ms=1785320000000).produce()
    )
    with open(path, "w") as fh:
        for s in statuses:
            fh.write(_json.dumps(_status_json(s)) + "\n")

    closed = "http://127.0.0.1:9"
    common = [
        "linear", "--source", "replay", "--replayFile", str(path),
        "--seconds", "0", "--backend", "cpu",
        "--batchBucket", "16", "--tokenBucket", "64",
        "--lightning", closed, "--twtweb", closed,
    ]
    d_plain, d_super = str(tmp_path / "ck1"), str(tmp_path / "ck2")
    plain = _run_app_group(
        common + ["--checkpointDir", d_plain], nprocs=2, ndev=2
    )
    sup = _run_app_group(
        common + ["--checkpointDir", d_super, "--superBatch", "2"],
        nprocs=2, ndev=2,
    )

    # r6 (Lean wire v2): the COALESCED group wire on a real process group —
    # each host packs its local shard segments, the global one-buffer wire
    # assembles per process, and the run stays stats-identical
    d_group = str(tmp_path / "ck3")
    grp = _run_app_group(
        common + [
            "--checkpointDir", d_group, "--superBatch", "2",
            "--wirePack", "group",
        ],
        nprocs=2, ndev=2,
    )

    def stat_lines(out):
        return [ln for ln in out.splitlines() if ln.startswith("count:")]

    assert stat_lines(sup[1]) == []  # one telemetry owner per run
    assert stat_lines(sup[0]) == stat_lines(plain[0])
    assert stat_lines(grp[0]) == stat_lines(plain[0])
    assert len(stat_lines(plain[0])) >= 5

    from twtml_tpu.checkpoint import Checkpointer

    w_plain, meta_p = Checkpointer(d_plain).restore()
    w_super, meta_s = Checkpointer(d_super).restore()
    w_group, meta_g = Checkpointer(d_group).restore()
    assert meta_p["count"] == meta_s["count"] == meta_g["count"] == 160
    np.testing.assert_allclose(w_super, w_plain, rtol=1e-6, atol=1e-8)
    # the group WIRE is byte-identical (tests/test_superwire.py pins the
    # unpack bit-for-bit, and single-process layouts train bitwise), but
    # across a real process group the coalesced program fuses differently
    # around the gloo collectives — last-ulp float drift, the same
    # cross-program tolerance the other multi-host weight comparisons in
    # this file use
    np.testing.assert_allclose(w_group, w_super, rtol=1e-4, atol=1e-8)
