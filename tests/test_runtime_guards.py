"""Runtime self-healing guards below the source layer (ISSUE 2): the fetch
watchdog (deadline / bounded re-issue / clean abort over the pooled
device_get), the publish circuit breaker (a dead dashboard stops taxing
the hot path), degraded-tunnel series shedding, and the satellite fixes
(stale checkpoint tmp sweep, wedged-producer stop warning, --webTimeout)."""

import logging
import os
import threading
import time

import numpy as np
import pytest

from twtml_tpu.apps.common import (
    FETCH_DEADLINE_MAX_S,
    FETCH_DEADLINE_MIN_S,
    FetchAbort,
    FetchPipeline,
    FetchWatchdog,
    SuperBatcher,
)
from twtml_tpu.config import ConfArguments
from twtml_tpu.telemetry import metrics as _metrics
from twtml_tpu.telemetry.breaker import CircuitBreaker


@pytest.fixture(autouse=True)
def fresh_metrics():
    _metrics.reset_for_tests()
    yield
    _metrics.reset_for_tests()


class FlakyFetchModel:
    """FakeModel whose per-batch fetch can stall or fail on chosen
    (batch, attempt) pairs — deterministic under the concurrent pool."""

    def __init__(self, slow: dict | None = None, errors: dict | None = None):
        self.dispatched = []
        self.slow = slow or {}  # {batch: {attempt: seconds}}
        self.errors = errors or {}  # {batch: {attempt}}
        self.attempts: dict = {}
        self._lock = threading.Lock()

    def step(self, batch):
        self.dispatched.append(batch)
        return {"i": np.asarray(batch)}

    def fetch_output(self, out):
        i = int(out["i"])
        with self._lock:
            n = self.attempts[i] = self.attempts.get(i, 0) + 1
        if n in self.errors.get(i, ()):
            raise ConnectionError(f"injected fetch failure b{i} attempt {n}")
        delay = self.slow.get(i, {}).get(n, 0.0)
        if delay:
            time.sleep(delay)
        return out


# -- fetch watchdog ----------------------------------------------------------

def test_fetch_deadline_derives_from_health_rtt(monkeypatch):
    class H:
        def __init__(self, ms):
            self.ms = ms

        def median_ms(self):
            return self.ms

    # no samples yet: maximally patient (first fetch of a run)
    assert FetchWatchdog(H(0)).deadline() == FETCH_DEADLINE_MAX_S
    # healthy tunnel RTT (~70ms): the floor binds
    assert FetchWatchdog(H(70)).deadline() == FETCH_DEADLINE_MIN_S
    # multi-second stall regime: the cap binds
    assert FetchWatchdog(H(10_000)).deadline() == FETCH_DEADLINE_MAX_S
    # env pin (the ops/test hook) overrides the derivation
    monkeypatch.setenv("TWTML_FETCH_DEADLINE_S", "0.25")
    assert FetchWatchdog(H(70)).deadline() == 0.25


def test_fetch_timeout_reissues_and_preserves_order():
    # batch 0's first fetch stalls past the deadline; the re-issue is fast.
    model = FlakyFetchModel(slow={0: {1: 0.8}})
    events = []
    pipe = FetchPipeline(
        model, lambda out, b, t, at_boundary: events.append(int(out["i"])),
        depth=3, fetch_deadline_s=0.1, fetch_retries=2,
    )
    for i in range(5):
        pipe.on_batch(i, 0.0)
    pipe.flush()
    assert events == [0, 1, 2, 3, 4]  # strict order survives the retry
    assert _metrics.get_registry().counter("fetch.retries").snapshot() >= 1
    assert _metrics.get_registry().counter("fetch.aborts").snapshot() == 0
    assert not pipe._watchdog.aborted


def test_fetch_error_reissues_and_delivers():
    model = FlakyFetchModel(errors={1: {1}})  # batch 1, first attempt only
    events = []
    pipe = FetchPipeline(
        model, lambda out, b, t, at_boundary: events.append(int(out["i"])),
        depth=2, fetch_deadline_s=5.0, fetch_retries=2,
    )
    for i in range(4):
        pipe.on_batch(i, 0.0)
    pipe.flush()
    assert events == [0, 1, 2, 3]
    assert _metrics.get_registry().counter("fetch.retries").snapshot() == 1


def test_fetch_abort_after_bounded_retries():
    # every attempt at batch 0 stalls: bounded retries, then a clean abort
    model = FlakyFetchModel(slow={0: {n: 0.5 for n in range(1, 10)}})
    events, aborted = [], []
    pipe = FetchPipeline(
        model, lambda out, b, t, at_boundary: events.append(int(out["i"])),
        depth=1, fetch_deadline_s=0.05, fetch_retries=1,
        abort=lambda: aborted.append(True),
    )
    pipe.on_batch(0, 0.0)
    with pytest.raises(FetchAbort):
        pipe.on_batch(1, 0.0)  # depth backpressure forces the emit
    assert pipe._watchdog.aborted
    assert aborted == [True]
    assert _metrics.get_registry().counter("fetch.aborts").snapshot() == 1
    # after the abort nothing more trains, and flush neither hangs nor raises
    dispatched = len(model.dispatched)
    pipe.on_batch(2, 0.0)
    assert len(model.dispatched) == dispatched
    pipe.flush()
    assert events == []


def test_superbatcher_partial_path_abort():
    model = FlakyFetchModel(slow={0: {n: 0.5 for n in range(1, 10)}})
    aborted = []
    sb = SuperBatcher(
        model, 4, lambda out, b, t, at_boundary: None,
        abort=lambda: aborted.append(True),
        fetch_deadline_s=0.05, fetch_retries=1,
    )
    sb.on_batch(np.asarray(0), 0.0)  # one batch < k: a partial group
    with pytest.raises(FetchAbort):
        sb._close_group()  # the partial path's pooled fetch stalls
    assert sb._watchdog.aborted and aborted == [True]
    # flush after the abort is a clean no-op (pool shut down, nothing leaks)
    sb.flush()


def test_flush_shuts_pool_down_even_when_handler_raises():
    # satellite: an exception re-raised during the drain must not leak
    # executor threads — the pool shuts down in a finally
    model = FlakyFetchModel()

    def handler(out, b, t, at_boundary):
        raise ValueError("handler blew up")

    pipe = FetchPipeline(model, handler, depth=4)
    pipe.on_batch(0, 0.0)
    with pytest.raises(ValueError):
        pipe.flush()
    assert pipe._pool._shutdown  # stdlib flag: shutdown() was called


# -- lockstep peer watchdog (unit; the process-level case lives in
# tests/test_distributed_multiprocess.py::test_lockstep_peer_death_...) ------

def test_watched_allgather_timeout_and_error_paths(monkeypatch):
    from jax.experimental import multihost_utils

    from twtml_tpu.streaming.context import _watched_allgather

    # a collective that never completes (hard-killed peer, no RST): the
    # watchdog gives up and returns None instead of hanging forever
    release = threading.Event()
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda arr: release.wait(5.0),
    )
    t0 = time.perf_counter()
    assert _watched_allgather(np.zeros(1), 0.1) is None
    assert time.perf_counter() - t0 < 2.0
    release.set()
    # a completing collective passes its result through
    monkeypatch.setattr(
        multihost_utils, "process_allgather", lambda arr: arr * 2
    )
    np.testing.assert_array_equal(
        _watched_allgather(np.ones(2), 1.0), 2 * np.ones(2)
    )
    # a raising collective (dead gloo peer = connection reset) propagates
    def boom(arr):
        raise ConnectionError("connection reset by peer")

    monkeypatch.setattr(multihost_utils, "process_allgather", boom)
    with pytest.raises(ConnectionError):
        _watched_allgather(np.ones(1), 1.0)


# -- publish circuit breaker -------------------------------------------------

def test_breaker_state_machine_with_half_open_probe():
    clock = {"t": 0.0}
    br = CircuitBreaker(
        "t1", failure_threshold=3, cooldown_s=10.0, now=lambda: clock["t"]
    )
    reg = _metrics.get_registry()
    # closed: flows; failures below the threshold keep it closed
    for _ in range(2):
        assert br.allow()
        br.record_failure()
    assert br.state == br.CLOSED
    assert br.allow()
    br.record_failure()  # 3rd consecutive: opens
    assert br.state == br.OPEN
    assert reg.gauge("publish.t1.breaker_open").snapshot() == 1
    # open: dropped-and-counted, no attempts
    assert not br.allow() and not br.allow()
    assert reg.counter("publish.t1.dropped").snapshot() == 2
    # cooldown elapsed: exactly ONE half-open probe is admitted
    clock["t"] = 10.0
    assert br.allow()
    assert br.state == br.HALF_OPEN
    assert not br.allow()  # probe outstanding: still shedding
    br.record_failure()  # probe failed: re-open for another cooldown
    assert br.state == br.OPEN
    assert not br.allow()
    # next probe succeeds: re-admit
    clock["t"] = 20.0
    assert br.allow()
    br.record_success()
    assert br.state == br.CLOSED
    assert reg.gauge("publish.t1.breaker_open").snapshot() == 0
    assert br.allow()
    # a success resets the consecutive-failure count
    br.record_failure()
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == br.CLOSED


def test_breaker_keeps_hot_path_fast_when_dashboard_is_dead():
    """Acceptance: with the breaker open, per-batch throughput must NOT
    collapse to the publish timeout — each publish used to block the batch
    handler for the full delay/timeout; after FAILURE_THRESHOLD failures
    the breaker drops them in microseconds."""
    from twtml_tpu.streaming import faults
    from twtml_tpu.telemetry.session_stats import SessionStats

    closed = "http://127.0.0.1:9"
    conf = ConfArguments().parse([
        "--twtweb", closed, "--lightning", closed, "--webTimeout", "0.5",
    ])
    # a slow-then-dead dashboard: every attempted publish costs 150ms
    faults.install_chaos("web:delay=0.15,web:error")
    try:
        session = SessionStats(conf)  # no open(): viz stays None
        real = np.array([1.0, 2.0])
        t0 = time.perf_counter()
        for i in range(5):  # FAILURE_THRESHOLD attempts, each slow
            session.update(10 * i, 2, 1.0, 1.0, 1.0, real, real)
        t_open = time.perf_counter()
        for i in range(20):  # breaker open: dropped, near-instant
            session.update(10 * i, 2, 1.0, 1.0, 1.0, real, real)
        t_end = time.perf_counter()
    finally:
        faults.uninstall_chaos()
    assert session._web_breaker.state == session._web_breaker.OPEN
    assert t_open - t0 >= 5 * 0.15  # the failures really were slow
    # 20 dropped publishes must cost nowhere near 20 x 150ms
    assert t_end - t_open < 1.0
    reg = _metrics.get_registry()
    assert reg.counter("publish.web.failures").snapshot() == 5
    assert reg.counter("publish.web.dropped").snapshot() >= 20


def test_series_sheds_to_every_nth_when_tunnel_degraded():
    from twtml_tpu.telemetry.session_stats import SERIES_SHED_EVERY, SessionStats

    closed = "http://127.0.0.1:9"
    conf = ConfArguments().parse(["--twtweb", closed, "--lightning", closed])
    session = SessionStats(conf)
    calls = {"stats": 0, "series": 0, "metrics": 0}

    class StubWeb:
        timeout = 2.0

        def stats(self, *a, **k):
            calls["stats"] += 1

        def series(self, *a, **k):
            calls["series"] += 1

        def metrics(self, *a, **k):
            calls["metrics"] += 1

    session.web = StubWeb()
    monitor = _metrics.get_health_monitor()
    monitor.phase = monitor.DEGRADED  # force the degraded phase
    real = np.array([1.0])
    for i in range(2 * SERIES_SHED_EVERY):
        session.update(i, 1, 1.0, 1.0, 1.0, real, real)
    # stats keep full per-batch resolution; series shed to every Nth
    assert calls["stats"] == 2 * SERIES_SHED_EVERY
    assert calls["series"] == 2
    shed = _metrics.get_registry().counter("publish.series_shed").snapshot()
    assert shed == 2 * SERIES_SHED_EVERY - 2
    # recovery restores per-batch series
    monitor.phase = monitor.HEALTHY
    before = calls["series"]
    for i in range(3):
        session.update(i, 1, 1.0, 1.0, 1.0, real, real)
    assert calls["series"] == before + 3


# -- satellite fixes ---------------------------------------------------------

def test_checkpointer_sweeps_stale_tmp_files(tmp_path):
    from twtml_tpu.checkpoint import Checkpointer

    d = str(tmp_path / "ck")
    ck = Checkpointer(d)
    ck.save(1, np.arange(4.0), {"count": 4})
    # a hard kill mid-write leaves a mkstemp temp file _prune never touches
    stale = os.path.join(d, "tmpdeadbeef.tmp")
    with open(stale, "wb") as fh:
        fh.write(b"partial checkpoint bytes")
    ck2 = Checkpointer(d)
    assert not [n for n in os.listdir(d) if n.endswith(".tmp")]
    weights, meta = ck2.restore()  # real checkpoints survive the sweep
    assert meta["step"] == 1
    np.testing.assert_array_equal(weights, np.arange(4.0))


def test_source_stop_names_wedged_producer_thread(caplog):
    from twtml_tpu.streaming.sources import Source

    release = threading.Event()

    class Wedged(Source):
        name = "wedged"

        def produce(self):
            release.wait(5.0)  # ignores the stop event: a stuck blocking call
            return iter(())

    src = Wedged()
    src.JOIN_TIMEOUT_S = 0.1
    src.start(lambda s: None)
    time.sleep(0.05)
    with caplog.at_level(logging.WARNING, logger="twtml.streaming.sources"):
        src.stop()
    release.set()
    warnings = [r for r in caplog.records if "did not stop" in r.message]
    assert len(warnings) == 1
    assert "twtml-source-wedged" in warnings[0].getMessage()


def test_web_timeout_flag_threads_through():
    from twtml_tpu.telemetry.session_stats import SessionStats

    assert ConfArguments().webTimeout == 2.0  # default preserved
    conf = ConfArguments().parse(["--webTimeout", "0.25"])
    assert conf.webTimeout == 0.25
    assert SessionStats(conf).web.timeout == 0.25


# -- abort refunds (ISSUE 3 satellite): every dispatched batch is either
# delivered to the handler or refunded — partial singles and coalesced/
# grouped dispatches alike, so cap accounting stays honest across aborts --


def test_superbatcher_partial_abort_refunds_dispatch():
    """The partial path's batch trains before its synchronous fetch; when
    that fetch aborts, the dispatch slot is refunded (trained-but-
    undelivered must not consume max_dispatch budget)."""
    model = FlakyFetchModel(slow={0: {n: 0.5 for n in range(1, 10)}})
    sb = SuperBatcher(
        model, 4, lambda out, b, t, at_boundary: None,
        fetch_deadline_s=0.05, fetch_retries=1, max_dispatch=8,
    )
    sb.on_batch(np.asarray(0), 0.0)
    with pytest.raises(FetchAbort):
        sb._close_group()
    assert sb._dispatched == 0  # the slot came back
    assert _metrics.get_registry().counter("fetch.refunds").snapshot() == 1
    sb.flush()  # clean no-op after the abort


def _flight_recorder(tmp_path):
    from twtml_tpu.telemetry import blackbox

    blackbox.uninstall()
    return blackbox.install(config={"app": "guards"}, out_dir=str(tmp_path))


def _assert_bundle(tmp_path, reason_fragment, event_kind):
    from tools import postmortem_report
    from twtml_tpu.telemetry import blackbox

    path = blackbox.last_dump_path()
    assert path and os.path.exists(path), "no post-mortem bundle dumped"
    assert postmortem_report.main([path]) == 0  # well-formed
    doc = postmortem_report.load_bundle(path)
    assert reason_fragment in doc["reason"], doc["reason"]
    assert any(e["kind"] == event_kind for e in doc["events"]), doc["events"]
    blackbox.uninstall()


def test_fetch_watchdog_abort_dumps_postmortem_bundle(tmp_path):
    """Abort path 1 (fetch-watchdog exhaustion): the abort hook funnels
    through ssc.request_abort, which dumps the flight recorder's bundle."""
    from twtml_tpu.streaming.context import StreamingContext

    _flight_recorder(tmp_path)
    ssc = StreamingContext()
    model = FlakyFetchModel(slow={0: {n: 0.5 for n in range(1, 10)}})
    pipe = FetchPipeline(
        model, lambda out, b, t, at_boundary: None,
        depth=1, fetch_deadline_s=0.05, fetch_retries=1,
        abort=ssc.request_abort,
    )
    pipe.on_batch(0, 0.0)
    with pytest.raises(FetchAbort):
        pipe.on_batch(1, 0.0)
    pipe.flush()
    assert ssc.failed
    _assert_bundle(tmp_path, "runtime guard", "fetch_abort")


def test_sentinel_budget_abort_dumps_postmortem_bundle(tmp_path):
    """Abort path 2 (sentinel rollback budget): the sentinel's abort rides
    the same funnel; the bundle records the rollbacks and the budget
    abort."""
    from types import SimpleNamespace

    from twtml_tpu.apps.common import DivergenceSentinel
    from twtml_tpu.streaming.context import StreamingContext

    _flight_recorder(tmp_path)
    ssc = StreamingContext()

    class _Ckpt:
        def rollback_to_verified(self):
            return {"step": 3}

    conf = ConfArguments().parse(
        ["--sentinelRollbacks", "1", "--sentinelWindow", "8"]
    )
    s = DivergenceSentinel(conf, None, _Ckpt(), ssc)
    out = SimpleNamespace(
        mse=float("nan"), real_stdev=1.0, pred_stdev=1.0, count=16
    )
    assert not s.admit(out, None)
    assert ssc.failed
    _assert_bundle(tmp_path, "runtime guard", "sentinel_abort")


def test_lockstep_peer_watchdog_abort_dumps_postmortem_bundle(
    tmp_path, monkeypatch
):
    """Abort path 3 (lockstep peer death): a cadence allgather that makes
    no progress fires the peer watchdog, which aborts through the funnel
    and leaves a bundle naming the watchdog."""
    from jax.experimental import multihost_utils

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.streaming.context import StreamingContext
    from twtml_tpu.streaming.sources import SyntheticSource

    _flight_recorder(tmp_path)
    monkeypatch.setenv("TWTML_LOCKSTEP_TIMEOUT_S", "0.2")
    release = threading.Event()
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda arr: release.wait(10.0),  # a peer that never answers
    )
    ssc = StreamingContext(batch_interval=0)
    ssc.source_stream(
        SyntheticSource(total=16, seed=7, base_ms=1785320000000),
        Featurizer(now_ms=1785320000000),
        row_bucket=16, token_bucket=64, device_hash=True,
    ).foreach_batch(lambda b, t: None)
    ssc.start(lockstep=True)
    assert ssc.await_termination(timeout=30)
    release.set()
    ssc.stop()
    assert ssc.failed
    _assert_bundle(tmp_path, "peer watchdog", "abort")


def test_cadence_disagreement_abort_dumps_postmortem_bundle(
    tmp_path, monkeypatch
):
    """Abort path 4 (rollback-count disagreement): fabricated gathered
    flags whose rollback column differs across hosts abort the group and
    leave a bundle naming the divergence."""
    import numpy as _np

    from jax.experimental import multihost_utils

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.streaming.context import StreamingContext
    from twtml_tpu.streaming.sources import SyntheticSource

    _flight_recorder(tmp_path)

    def disagreeing(arr):
        other = _np.array(arr, copy=True)
        other[3] += 1  # the peer claims one more sentinel rollback
        return _np.stack([_np.asarray(arr), other])

    monkeypatch.setattr(multihost_utils, "process_allgather", disagreeing)
    ssc = StreamingContext(batch_interval=0)
    ssc.source_stream(
        SyntheticSource(total=16, seed=7, base_ms=1785320000000),
        Featurizer(now_ms=1785320000000),
        row_bucket=16, token_bucket=64, device_hash=True,
    ).foreach_batch(lambda b, t: None)
    ssc.start(lockstep=True)
    assert ssc.await_termination(timeout=30)
    ssc.stop()
    assert ssc.failed
    assert _metrics.get_registry().counter(
        "lockstep.rollback_disagreements"
    ).snapshot() == 1
    _assert_bundle(tmp_path, "disagree", "abort")


def test_superbatcher_flush_refunds_undelivered_groups():
    """Grouped dispatches (the coalesced-wire path included) that are
    in flight when the tunnel wedges: flush drops them AND refunds every
    batch they carried."""
    import time as _time

    import jax

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource

    class WedgedGroupFetch:
        """Real learner, wedged pooled fetches — groups dispatch fine and
        every fetch stalls past the watchdog deadline."""

        accepts_packed = True

        def __init__(self):
            self.inner = StreamingLinearRegressionWithSGD(num_iterations=2)

        def step(self, b):
            return self.inner.step(b)

        def step_many(self, stacked):
            return self.inner.step_many(stacked)

        def fetch_output(self, out):
            _time.sleep(0.5)
            return jax.device_get(out)

        fetch_output_many = fetch_output

    statuses = list(
        SyntheticSource(total=64, seed=3, base_ms=1785320000000).produce()
    )
    feat = Featurizer(now_ms=1785320000000)
    batches = [
        feat.featurize_batch_ragged(
            statuses[i * 16 : (i + 1) * 16], row_bucket=16, unit_bucket=512,
            pre_filtered=True,
        )
        for i in range(4)
    ]
    for wire_pack in ("group", "stacked"):
        _metrics.reset_for_tests()
        aborted = []
        sb = SuperBatcher(
            WedgedGroupFetch(), 2, lambda out, b, t, at_boundary: None,
            fetch_depth=4, fetch_deadline_s=0.05, fetch_retries=1,
            abort=lambda: aborted.append(True), wire_pack=wire_pack,
        )
        for i, b in enumerate(batches):
            sb.on_batch(b, float(i))
        assert sb._dispatched == 4  # two groups of two, both in flight
        sb.flush()  # abort inside the drain is swallowed; refunds land
        assert aborted == [True]
        assert sb._dispatched == 0, wire_pack
        assert (
            _metrics.get_registry().counter("fetch.refunds").snapshot() == 4
        ), wire_pack
