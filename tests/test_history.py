"""Telemetry historian (ISSUE 20): durable long-horizon time series +
phase-segmented cross-run perf regression sentinel.

The laws under test, in the order the ISSUE states them:
- **durability discipline** (the journal's): CRC32-framed records in
  rotated segments, torn tails truncated LOUDLY on recovery (and skipped,
  never fatal, by the offline reader), ``--historyMaxMb`` enforced by
  dropping whole oldest segments (counted), restart-append continuity —
  one directory accumulates a multi-run timeline;
- **SIGKILL reconstruction** (ACCEPTANCE): a killed run's leftover
  segments ALONE rebuild the healthy/degraded phase intervals and the
  least-squares RSS slope, and ``tools/history_report.py`` exits 0 on
  them;
- **perfGuard round trip** (ACCEPTANCE): run 1 stamps healthy-phase
  stage-clock medians into baseline.json at clean shutdown; run 2's
  SUSTAINED seeded regression fires ONE warn-only blackbox event per
  episode + ``perf.regressions`` — and never anything louder;
- **zero added fetches / zero added collectives** with sampling ON,
  COUNTED over a real lockstep run (the PR 5/8/16 idiom);
- **off bit-parity**: a ``--history off`` app run lands BIT-identical
  weights and never creates the history directory;
- the ``History`` wire view, the blackbox bundle's history tail, the
  postmortem rendering, ``tools/history_report.py`` exit codes, and the
  run-id/fingerprint provenance seam (utils/runid.py).
"""

import json
import os
import struct
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools import history_report  # noqa: E402
from tools import postmortem_report  # noqa: E402
from twtml_tpu.config import ConfArguments  # noqa: E402
from twtml_tpu.telemetry import blackbox as blackbox_mod  # noqa: E402
from twtml_tpu.telemetry import historian as H  # noqa: E402
from twtml_tpu.telemetry import metrics as _metrics  # noqa: E402
from twtml_tpu.telemetry import sideband as _sideband  # noqa: E402

NOW_MS = 1785320000000
CLOSED = "http://127.0.0.1:9"


@pytest.fixture(autouse=True)
def _fresh_state():
    _metrics.reset_for_tests()
    _sideband.reset_for_tests()
    H.reset_for_tests()
    yield
    H.reset_for_tests()
    _metrics.reset_for_tests()
    _sideband.reset_for_tests()


class _Clock:
    """Drives the TWTML_NOW_MS seam sample-by-sample."""

    def __init__(self, monkeypatch, t0=NOW_MS):
        self._mp = monkeypatch
        self.t = t0
        self.set(t0)

    def set(self, t_ms):
        self.t = t_ms
        self._mp.setenv("TWTML_NOW_MS", str(int(t_ms)))

    def tick(self, dt_ms=60000):
        self.set(self.t + dt_ms)


def _seed_stages(monkeypatch):
    """Replace the cumulative stage clock with a driveable dict; bump the
    returned dict's values to seed per-sample deltas."""
    cum = {}
    monkeypatch.setattr(_sideband, "stage_seconds", lambda: dict(cum))
    return cum


def _seed_rss(monkeypatch):
    box = {"mb": 100.0}
    import twtml_tpu.utils.rss as rss_mod

    monkeypatch.setattr(rss_mod, "rss_mb", lambda: box["mb"])
    return box


def _flip_phase(phase, t_s):
    mon = _metrics.get_health_monitor()
    with mon._lock:
        mon.phase = phase
        mon.transitions.append((t_s, phase))


# ---------------------------------------------------------------------------
# durability discipline: frames, restart continuity, torn tails, ceiling


def test_frame_roundtrip_and_restart_continuity(tmp_path, monkeypatch):
    clock = _Clock(monkeypatch)
    d = str(tmp_path / "hist")
    H.configure(d, run_id=1, fingerprint="aaa111")
    for _ in range(3):
        clock.tick()
        H.sample()
    H.uninstall()

    recs = H.read_series(d)
    assert [r["k"] for r in recs] == ["r", "s", "s", "s"]
    assert recs[0]["run_id"] == 1 and recs[0]["fingerprint"] == "aaa111"
    assert [r["seq"] for r in recs if r["k"] == "s"] == [1, 2, 3]

    # restart: the second run APPENDS after the recovered tail — one
    # directory is one multi-run timeline
    h2 = H.configure(d, run_id=2, fingerprint="bbb222")
    assert h2.next_seq == 5  # 4 recovered records + this run's header
    clock.tick()
    H.sample()
    H.uninstall()
    recs = H.read_series(d)
    assert [r["run_id"] for r in recs if r["k"] == "r"] == [1, 2]
    assert len([r for r in recs if r["k"] == "s"]) == 4


def test_torn_tail_truncates_loudly_and_reader_skips_it(
    tmp_path, monkeypatch
):
    clock = _Clock(monkeypatch)
    d = str(tmp_path / "hist")
    H.configure(d, run_id=1)
    for _ in range(3):
        clock.tick()
        H.sample()
    H.uninstall()

    segs = sorted(p for p in os.listdir(d) if p.endswith(".twh"))
    assert len(segs) == 1
    path = os.path.join(d, segs[0])
    good_size = os.path.getsize(path)
    with open(path, "ab") as fh:  # a kill -9 mid-append: torn mid-payload
        fh.write(H.MAGIC + struct.pack("<II", 500, 12345) + b"partial")

    # the OFFLINE reader (a dead run's directory): torn tail skipped,
    # every complete record before it survives — never an error
    recs = H.read_series(d)
    assert len(recs) == 4

    # LIVE recovery truncates it loudly and appends after
    H.configure(d, run_id=2)
    reg = _metrics.get_registry()
    assert reg.counter("history.torn_tails").snapshot() == 1
    assert os.path.getsize(path) == good_size
    clock.tick()
    H.sample()
    H.uninstall()
    recs = H.read_series(d)
    assert [r["k"] for r in recs] == ["r", "s", "s", "s", "r", "s"]


def test_segment_rotation_and_disk_ceiling(tmp_path):
    d = str(tmp_path / "hist")
    h = H.configure(d, max_mb=1)  # segment_bytes = 256 KB
    assert h.segment_bytes == 256 * 1024
    pad = "x" * 20000
    for i in range(80):  # ~1.6 MB of records through a 1 MB ceiling
        h._write({"k": "s", "t_ms": NOW_MS + i, "rss_mb": 1.0, "pad": pad})
    reg = _metrics.get_registry()
    assert reg.counter("history.segments_dropped").snapshot() >= 1
    assert h.disk_bytes() <= h.max_bytes + h.segment_bytes
    segs = h._segments()
    assert len(segs) >= 2            # rotation happened
    assert segs[0][0] > 0            # ...and the OLDEST segment was dropped
    assert reg.gauge("history.disk_mb").snapshot() > 0
    assert H.read_series(d)          # survivors parse end to end
    H.uninstall()


# ---------------------------------------------------------------------------
# ACCEPTANCE: a SIGKILLed run's leftovers alone rebuild the timeline


def test_sigkill_leftovers_reconstruct_phases_and_slope(
    tmp_path, monkeypatch, capsys
):
    clock = _Clock(monkeypatch)
    rss = _seed_rss(monkeypatch)
    d = str(tmp_path / "hist")
    H.configure(d, run_id=5, fingerprint="deadbeef0001")

    def burst(n, phase=None):
        for _ in range(n):
            clock.tick()          # 1 min per sample
            rss["mb"] += 2.0      # 2 MB per sample -> 2 MB/min slope
            if phase is not None:
                _flip_phase(phase, clock.t / 1000.0)
                phase = None
            H.sample()

    burst(5)
    burst(5, phase="degraded")
    burst(5, phase="healthy")
    # the kill: no stamp, no clean close — plus a torn frame on the tail
    H.uninstall()
    seg = sorted(p for p in os.listdir(d) if p.endswith(".twh"))[-1]
    with open(os.path.join(d, seg), "ab") as fh:
        fh.write(b"\x00garbage-from-a-kill-mid-write")

    records = H.read_series(d)
    intervals = H.phase_intervals(records)
    assert [iv["phase"] for iv in intervals] == [
        "healthy", "degraded", "healthy",
    ]
    assert [iv["samples"] for iv in intervals] == [5, 5, 5]
    assert H.rss_slope(records) == pytest.approx(2.0, rel=0.05)
    trends = H.phase_trends(records)
    assert set(trends) == {"healthy", "degraded"}
    assert trends["healthy"]["samples"] == 10

    # the CLI check on the leftovers: exit 0 + the same derivations
    assert history_report.main([d, "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["samples"] == 15
    assert len(summary["phase_intervals"]) == 3
    assert summary["rss_slope_mb_per_min"] == pytest.approx(2.0, rel=0.05)
    assert summary["runs"][0]["run_id"] == 5
    assert history_report.main([d]) == 0  # rendered form, same verdict
    assert "degraded" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# ACCEPTANCE: the cross-run perfGuard round trip (warn-only, episodic)


def test_perf_guard_baseline_round_trip_and_sustained_regression(
    tmp_path, monkeypatch
):
    clock = _Clock(monkeypatch)
    cum = _seed_stages(monkeypatch)
    d = str(tmp_path / "hist")
    rec = blackbox_mod.install(config={})
    reg = _metrics.get_registry()
    try:
        # run 1: steady 2.0 ms/tick featurize -> stamped at clean shutdown
        cum["featurize"] = 0.0
        H.configure(d, run_id=1, fingerprint="cfg1")
        for _ in range(10):
            clock.tick()
            cum["featurize"] += 0.002
            H.sample()
        base = H.stamp_baseline()
        assert base == {
            "version": 1, "run_id": 1, "fingerprint": "cfg1",
            "samples": 10, "stages_ms": {"featurize": 2.0},
        }
        H.uninstall()
        assert json.load(
            open(os.path.join(d, H.BASELINE_NAME))
        )["run_id"] == 1

        # run 2 loads the baseline; a SUSTAINED 2.5x regression fires ONE
        # episode after GUARD_WINDOW consecutive healthy breaches
        h2 = H.configure(d, run_id=2, fingerprint="cfg1")
        assert h2.baseline is not None
        for _ in range(3):  # at baseline: no breach run
            clock.tick()
            cum["featurize"] += 0.002
            H.sample()
        assert reg.counter("perf.regressions").snapshot() == 0
        for i in range(H.GUARD_WINDOW):
            clock.tick()
            cum["featurize"] += 0.005  # 5.0 ms/tick = 2.5x
            H.sample()
            if i < H.GUARD_WINDOW - 1:  # a burst below the window is noise
                assert reg.counter("perf.regressions").snapshot() == 0
        assert reg.counter("perf.regressions").snapshot() == 1
        events = [
            e for e in rec.bundle("t")["events"]
            if e["kind"] == "perf_regression"
        ]
        assert len(events) == 1
        assert events[0]["stage"] == "featurize"
        assert events[0]["ratio"] == pytest.approx(2.5, abs=0.01)
        assert events[0]["baseline_run_id"] == 1

        for _ in range(4):  # episode latch: no re-fire while sustained
            clock.tick()
            cum["featurize"] += 0.005
            H.sample()
        assert reg.counter("perf.regressions").snapshot() == 1
        clock.tick()
        cum["featurize"] += 0.002  # recovery closes the episode
        H.sample()
        for _ in range(H.GUARD_WINDOW):  # a NEW sustained breach re-fires
            clock.tick()
            cum["featurize"] += 0.005
            H.sample()
        assert reg.counter("perf.regressions").snapshot() == 2
        H.uninstall()

        # --perfGuard off: same breach pattern, sentinel fully quiet and
        # the clean-shutdown stamp is withheld
        h3 = H.configure(d, run_id=3, perf_guard=False)
        for _ in range(H.GUARD_WINDOW + 2):
            clock.tick()
            cum["featurize"] += 0.005
            H.sample()
        assert reg.counter("perf.regressions").snapshot() == 2
        assert H.stamp_baseline() is None
        assert h3.baseline is not None  # loaded for reports, just not armed
    finally:
        blackbox_mod.uninstall()


def test_guard_ignores_noise_scale_stages(tmp_path, monkeypatch):
    """Stages under GUARD_MIN_BASELINE_MS are jitter on the one-core host:
    a 0.01 -> 0.05 ms "5x" never pages."""
    clock = _Clock(monkeypatch)
    cum = _seed_stages(monkeypatch)
    d = str(tmp_path / "hist")
    cum["tiny"] = 0.0
    H.configure(d, run_id=1)
    for _ in range(10):
        clock.tick()
        cum["tiny"] += 0.00001  # 0.01 ms/tick baseline
        H.sample()
    assert H.stamp_baseline()["stages_ms"]["tiny"] == 0.01
    H.uninstall()
    H.configure(d, run_id=2)
    for _ in range(H.GUARD_WINDOW + 2):
        clock.tick()
        cum["tiny"] += 0.00005  # "5x regression" at noise scale
        H.sample()
    assert _metrics.get_registry().counter(
        "perf.regressions"
    ).snapshot() == 0


def test_baseline_needs_enough_healthy_samples(tmp_path, monkeypatch):
    clock = _Clock(monkeypatch)
    d = str(tmp_path / "hist")
    H.configure(d, run_id=1)
    for _ in range(H.BASELINE_MIN_SAMPLES - 1):
        clock.tick()
        H.sample()
    assert H.stamp_baseline() is None  # too few to be a verdict
    assert not os.path.exists(os.path.join(d, H.BASELINE_NAME))


# ---------------------------------------------------------------------------
# views: History wire view, blackbox bundle tail, postmortem rendering


def test_view_bundle_tail_and_postmortem_rendering(tmp_path, monkeypatch):
    clock = _Clock(monkeypatch)
    rss = _seed_rss(monkeypatch)
    d = str(tmp_path / "hist")
    rec = blackbox_mod.install(config={})
    try:
        assert H.last_history() is None and H.bundle_tail() is None
        H.configure(d, run_id=9, fingerprint="fff999")
        for _ in range(3):
            clock.tick()
            rss["mb"] += 1.0
            H.sample()
        view = H.last_history()
        assert view["samples"] == 3 and view["runId"] == 9
        assert view["phase"] == "healthy"
        assert len(view["rss"]) == 3 and view["rssMb"] == rss["mb"]
        assert view["regressions"] == 0
        from twtml_tpu.telemetry.api_types import History

        History(**view)  # the view IS the wire type, field for field

        bundle = rec.bundle("test-death")
        assert bundle["history"]["run_id"] == 9
        assert len(bundle["history"]["samples"]) == 3
        # postmortem narrates the minutes before death...
        summary = postmortem_report.summarize(bundle)
        assert summary["history"]["samples"] == 3
        assert "history tail (run 9)" in postmortem_report.render(summary)
        # ...and history_report accepts the bundle as a source (exit 0)
        bpath = tmp_path / "bundle.json"
        bpath.write_text(json.dumps(bundle))
        assert history_report.main([str(bpath)]) == 0

        H.uninstall()
        assert H.last_history() is None
        assert rec.bundle("after")["history"] is None
        assert postmortem_report.summarize(
            rec.bundle("after")
        )["history"] is None
    finally:
        blackbox_mod.uninstall()


def test_report_exit_codes(tmp_path, monkeypatch, capsys):
    empty = tmp_path / "empty"
    empty.mkdir()
    assert history_report.main([str(empty)]) == 2  # no records
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert history_report.main([str(bad)]) == 2    # malformed bundle
    assert history_report.main([]) == 2            # usage
    capsys.readouterr()
    clock = _Clock(monkeypatch)
    d = str(tmp_path / "hist")
    H.configure(d, run_id=1)
    clock.tick()
    H.sample()
    H.uninstall()
    assert history_report.main([d]) == 0


# ---------------------------------------------------------------------------
# THE counted constraint: sampling adds zero fetches, zero collectives
# over a real lockstep run (the PR 5/8/16 law)


def test_sampling_adds_no_fetches_and_no_collectives(tmp_path, monkeypatch):
    import jax
    from jax.experimental import multihost_utils

    from twtml_tpu.apps.common import FetchPipeline
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.context import StreamingContext
    from twtml_tpu.streaming.sources import SyntheticSource

    jax.devices()  # lock the conftest backend
    calls = {"allgather": 0, "get": 0}
    real_ag = multihost_utils.process_allgather

    def counting_ag(arr):
        calls["allgather"] += 1
        return real_ag(arr)

    monkeypatch.setattr(multihost_utils, "process_allgather", counting_ag)
    real_get = jax.device_get

    def counting_get(x):
        calls["get"] += 1
        return real_get(x)

    monkeypatch.setattr(jax, "device_get", counting_get)

    d = str(tmp_path / "hist")
    H.configure(d, run_id=1)
    ssc = StreamingContext(batch_interval=0)
    stream = ssc.source_stream(
        SyntheticSource(total=64, seed=7, base_ms=NOW_MS),
        Featurizer(now_ms=NOW_MS),
        row_bucket=16, token_bucket=64, device_hash=True,
    )
    model = StreamingLinearRegressionWithSGD(num_iterations=2)

    def handle(out, b, t, at_boundary=True):
        H.sample()  # the publish-seam cadence, once per delivered batch

    pipe = FetchPipeline(model, handle, deterministic=True)
    stream.foreach_batch(pipe.on_batch)
    ssc.start(lockstep=True)
    assert ssc.await_termination(timeout=120)
    ssc.stop()
    pipe.flush()
    assert not ssc.failed
    assert ssc.batches_processed >= 4

    reg = _metrics.get_registry().snapshot()
    ticks = reg["counters"]["lockstep.ticks"]
    # ZERO added collectives: still exactly ONE allgather per lockstep tick
    assert calls["allgather"] == ticks
    # ZERO added host fetches: one per dispatched batch — every sample was
    # a pure host-side snapshot of already-computed views
    assert calls["get"] == ssc.batches_processed
    assert reg["counters"]["history.samples"] == ssc.batches_processed
    samples = [r for r in H.read_series(d) if r.get("k") == "s"]
    assert len(samples) == ssc.batches_processed
    H.uninstall()


# ---------------------------------------------------------------------------
# app-level acceptance: default-on counting + OFF bit-parity


BASE = [
    "--source", "replay", "--seconds", "0", "--backend", "cpu",
    "--batchBucket", "16", "--tokenBucket", "64", "--master", "local[1]",
    "--lightning", CLOSED, "--twtweb", CLOSED, "--webTimeout", "0.2",
]


def _corpus_file(tmp_path, total=8 * 16, seed=51):
    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    statuses = list(
        SyntheticSource(total=total, seed=seed, base_ms=NOW_MS).produce()
    )
    path = tmp_path / "tweets.jsonl"
    with open(path, "w") as fh:
        for s in statuses:
            fh.write(json.dumps(_status_json(s)) + "\n")
    return path


def _run_counting_fetches(conf_args):
    import jax

    from twtml_tpu.apps import linear_regression as app

    jax.devices()
    calls = {"n": 0}
    real = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real(x)

    jax.device_get = counting
    try:
        totals = app.run(ConfArguments().parse(list(conf_args)))
    finally:
        jax.device_get = real
    return totals, calls["n"]


def test_app_default_history_counts_and_off_is_bit_exact(
    tmp_path, monkeypatch
):
    """ACCEPTANCE: a real app run with the DEFAULT --history auto (on via
    --checkpointDir) fetches exactly once per batch, leaves CRC-valid
    segments behind, and a --history off run lands BIT-identical weights
    with no history directory at all."""
    from twtml_tpu.checkpoint import Checkpointer

    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    monkeypatch.setenv("TWTML_RUN_ID_FILE", str(tmp_path / "runid"))
    path = _corpus_file(tmp_path)
    totals_on, fetches_on = _run_counting_fetches(
        BASE + ["--replayFile", str(path),
                "--checkpointDir", str(tmp_path / "ck_on"),
                "--checkpointEvery", "1"]
    )
    assert totals_on["batches"] == 8
    assert fetches_on == 8  # ONE device_get per batch, the historian adds none
    hist_dir = str(tmp_path / "ck_on" / "history")
    recs = H.read_series(hist_dir)
    heads = [r for r in recs if r["k"] == "r"]
    assert len(heads) == 1 and heads[0]["run_id"] >= 1
    assert len(heads[0]["fingerprint"]) == 12
    samples = [r for r in recs if r["k"] == "s"]
    assert samples and samples[0]["rss_mb"] > 0
    assert history_report.main([hist_dir]) == 0
    w_on, _meta = Checkpointer(str(tmp_path / "ck_on")).restore()

    totals_off, fetches_off = _run_counting_fetches(
        BASE + ["--replayFile", str(path), "--history", "off",
                "--checkpointDir", str(tmp_path / "ck_off"),
                "--checkpointEvery", "1"]
    )
    assert totals_off["batches"] == 8
    assert fetches_off == 8
    assert not os.path.exists(str(tmp_path / "ck_off" / "history"))
    assert H.last_history() is None  # module fully off after the off run
    w_off, _ = Checkpointer(str(tmp_path / "ck_off")).restore()
    # the bit-parity law: identical weights with the historian on or off
    assert np.asarray(w_on).tobytes() == np.asarray(w_off).tobytes()
    assert totals_on["count"] == totals_off["count"]


def test_history_on_without_checkpoint_dir_refuses(tmp_path):
    from twtml_tpu.apps.common import install_historian

    conf = ConfArguments().parse(BASE + ["--history", "on"])
    with pytest.raises(SystemExit):
        install_historian(conf)


# ---------------------------------------------------------------------------
# config resolution + the provenance seam (utils/runid.py)


def test_effective_history_resolution(tmp_path):
    conf = ConfArguments().parse(list(BASE))
    assert conf.history == "auto" and not conf.effective_history()
    conf = ConfArguments().parse(
        BASE + ["--checkpointDir", str(tmp_path / "ck")]
    )
    assert conf.effective_history()  # auto follows the checkpoint flag
    conf = ConfArguments().parse(
        BASE + ["--checkpointDir", str(tmp_path / "ck"),
                "--history", "off"]
    )
    assert not conf.effective_history()
    for bad in (["--history", "sometimes"], ["--historyMaxMb", "0"],
                ["--perfGuard", "abort"], ["--perfGuardRatio", "0.9"]):
        with pytest.raises(SystemExit):
            ConfArguments().parse(BASE + bad)


def test_run_id_monotonic_and_fingerprint_stable(tmp_path, monkeypatch):
    from twtml_tpu.utils.runid import config_fingerprint, next_run_id

    monkeypatch.setenv("TWTML_RUN_ID_FILE", str(tmp_path / "runid"))
    ids = [next_run_id() for _ in range(3)]
    assert ids == [1, 2, 3]  # monotonic across "runs" on one host

    fp1 = config_fingerprint({"batch": 2048, "wire": "ragged"})
    fp2 = config_fingerprint({"wire": "ragged", "batch": 2048})
    assert fp1 == fp2 and len(fp1) == 12  # order-free, compact
    assert fp1 != config_fingerprint({"batch": 1024, "wire": "ragged"})
    conf = ConfArguments().parse(list(BASE))
    assert len(config_fingerprint(conf)) == 12  # real config objects too
