"""Ragged units wire (features/batch.RaggedUnitBatch): the concatenated
units + offsets wire must produce BIT-IDENTICAL training to the padded
UnitBatch wire — the device-side gather re-pad + ASCII fold replaces the
host-side pad copy exactly. Parity law: features/hashing.py / the padded
Status path is ground truth; every fast path carries differential tests."""

import numpy as np
import pytest

from twtml_tpu.features.batch import RAGGED_UNIT_MULTIPLE, RaggedUnitBatch
from twtml_tpu.features.featurizer import Featurizer, Status
from twtml_tpu.models import (
    StreamingLinearRegressionWithSGD,
    StreamingLogisticRegressionWithSGD,
)
from twtml_tpu.streaming.sources import SyntheticSource


def rt(text, label=500):
    return Status(
        text="RT",
        retweeted_status=Status(text=text, retweet_count=label,
                                followers_count=1234),
    )


def synthetic(n=96, seed=13):
    return list(
        SyntheticSource(total=n, seed=seed, base_ms=1785320000000).produce()
    )


def assert_identical_training(statuses, model_cls=StreamingLinearRegressionWithSGD,
                              rows=32, feat_kw=None, model_kw=None):
    feat = Featurizer(now_ms=1785320000000, **(feat_kw or {}))
    chunks = [statuses[i : i + rows] for i in range(0, len(statuses), rows)]

    padded_model = model_cls(num_iterations=5, **(model_kw or {}))
    ragged_model = model_cls(num_iterations=5, **(model_kw or {}))
    for chunk in chunks:
        pb = feat.featurize_batch_units(chunk, row_bucket=rows, unit_bucket=64)
        rb = feat.featurize_batch_ragged(chunk, row_bucket=rows, unit_bucket=64)
        out_p = padded_model.step(pb)
        out_r = ragged_model.step(rb)
        for field_p, field_r in zip(out_p, out_r):
            np.testing.assert_array_equal(
                np.asarray(field_p), np.asarray(field_r)
            )
    np.testing.assert_array_equal(
        padded_model.latest_weights, ragged_model.latest_weights
    )


def test_ragged_matches_padded_synthetic_stream():
    assert_identical_training(synthetic())


def test_ragged_matches_padded_logistic():
    assert_identical_training(
        synthetic(), model_cls=StreamingLogisticRegressionWithSGD
    )


def test_ragged_matches_padded_unicode_and_edge_rows():
    statuses = [
        rt("MiXeD CaSe ASCII tweet!"),
        rt("ünïcode ÉMOJI \U0001f600 tweet"),  # astral char: 2 units
        rt("x"),  # single-unit row: the sliding(2) special case
        rt("ÀÈÌ UPPER with accents"),
        rt("plain lower ascii"),
    ] * 7
    assert_identical_training(statuses, rows=8)
    assert_identical_training(
        statuses, rows=8, feat_kw={"normalize_accents": True}
    )


def test_ragged_wire_shape_and_narrowing():
    feat = Featurizer(now_ms=0)
    rb = feat.featurize_batch_ragged(
        [rt("hello world")] * 10, row_bucket=16, unit_bucket=32
    )
    assert isinstance(rb, RaggedUnitBatch)
    assert rb.units.dtype == np.uint8  # all-ASCII narrow wire
    assert rb.units.shape == (RAGGED_UNIT_MULTIPLE,)
    assert rb.offsets.shape == (17,)
    assert rb.row_len == 32
    assert rb.num_valid == 10
    # non-ASCII rows keep the full uint16 schema
    rb16 = feat.featurize_batch_ragged([rt("héllo")] * 4, row_bucket=8)
    assert rb16.units.dtype == np.uint16


def test_ragged_empty_batch():
    feat = Featurizer(now_ms=0)
    rb = feat.featurize_batch_ragged([], row_bucket=8, unit_bucket=16)
    model = StreamingLinearRegressionWithSGD(num_iterations=5)
    out = model.step(rb)
    assert float(out.count) == 0.0
    np.testing.assert_array_equal(
        model.latest_weights, np.zeros_like(model.latest_weights)
    )


def test_ragged_2e18_gram_config():
    """The ragged wire through the 2^18 Gram-domain config (BASELINE #4) —
    the config whose throughput the wire work targets."""
    statuses = synthetic(n=64)
    assert_identical_training(
        statuses, rows=32,
        feat_kw={"num_text_features": 2**18},
        model_kw={"num_text_features": 2**18, "l2_reg": 0.1},
    )


@pytest.mark.parametrize("total", [3, 40])
def test_ragged_unit_bucket_growth(total):
    """Unpinned unit bucket: the rebuilt row length grows per batch like the
    padded wire's (same _bucket policy), so mixed streams stay consistent."""
    feat = Featurizer(now_ms=0)
    text = "a" * total
    rb = feat.featurize_batch_ragged([rt(text)], row_bucket=4)
    pb = feat.featurize_batch_units([rt(text)], row_bucket=4)
    assert rb.row_len == pb.units.shape[1]


def test_linear_app_ragged_identical_stats(tmp_path, capsys):
    """--wire ragged through the REAL flagship app prints the identical
    per-batch stats lines and totals as --wire padded."""
    import json

    import jax

    from tools.bench_suite import _status_json
    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.config import ConfArguments

    jax.devices()  # lock the conftest backend before local[1]

    path = tmp_path / "tweets.jsonl"
    with open(path, "w") as fh:
        for s in synthetic(n=5 * 16, seed=21):
            fh.write(json.dumps(_status_json(s)) + "\n")

    def run(wire):
        conf = ConfArguments().parse([
            "--source", "replay", "--replayFile", str(path),
            "--seconds", "0", "--backend", "cpu",
            "--batchBucket", "16", "--tokenBucket", "64",
            "--master", "local[1]", "--wire", wire,
        ])
        capsys.readouterr()
        totals = app.run(conf)
        lines = [
            ln for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("count:")
        ]
        return totals, lines

    totals_p, lines_p = run("padded")
    totals_r, lines_r = run("ragged")
    # stream_seconds is wall-clock (r4, for the suite's startup split)
    totals_p.pop("stream_seconds", None); totals_r.pop("stream_seconds", None)
    assert totals_r == totals_p
    assert lines_r == lines_p
    assert len(lines_p) >= 5


def test_ragged_flag_gates():
    """The loud incompatibility gate that remains (host hashing), and the
    r4 capability the r3 mesh gate gave way to: build_model accepts the
    ragged wire on a mesh (shard-aligned segments,
    tests/test_ragged_sharded.py)."""
    from twtml_tpu.apps.common import build_model, build_source
    from twtml_tpu.config import ConfArguments
    from twtml_tpu.parallel import ParallelSGDModel

    import jax

    jax.devices()

    base = ["--wire", "ragged", "--source", "synthetic"]
    model, row_multiple = build_model(ConfArguments().parse(base))
    assert isinstance(model, ParallelSGDModel)  # 8-device mesh, no gate
    assert row_multiple == 8
    with pytest.raises(SystemExit):
        build_source(ConfArguments().parse(base + ["--hashOn", "host"]))


def test_ragged_block_ingest_matches_padded(tmp_path):
    """The ragged wire from COLUMNAR BLOCKS (the native data loader's
    format — no pad copy at all: the block already holds concatenated
    units + offsets) trains bit-identically to the padded block path."""
    import json

    from tools.bench_suite import _status_json
    from twtml_tpu.features.blocks import iter_row_chunks
    from twtml_tpu.streaming.sources import BlockReplayFileSource

    path = tmp_path / "tweets.jsonl"
    statuses = synthetic(n=96, seed=31)
    # a couple of non-ASCII rows exercise the redo/uint16 path
    statuses[3] = rt("ünïcode BLOCK tweet É")
    statuses[40] = rt("MiXeD Ascii ROW")
    with open(path, "w") as fh:
        for s in statuses:
            fh.write(json.dumps(_status_json(s)) + "\n")

    feat = Featurizer(now_ms=1785320000000)
    blocks = list(BlockReplayFileSource(str(path)).produce())
    chunks = list(iter_row_chunks(blocks, 32))

    padded_model = StreamingLinearRegressionWithSGD(num_iterations=5)
    ragged_model = StreamingLinearRegressionWithSGD(num_iterations=5)
    for chunk in chunks:
        pb = feat.featurize_parsed_block(chunk, row_bucket=32, unit_bucket=64)
        rb = feat.featurize_parsed_block(
            chunk, row_bucket=32, unit_bucket=64, ragged=True
        )
        assert isinstance(rb, RaggedUnitBatch)
        out_p = padded_model.step(pb)
        out_r = ragged_model.step(rb)
        for field_p, field_r in zip(out_p, out_r):
            np.testing.assert_array_equal(
                np.asarray(field_p), np.asarray(field_r)
            )
    np.testing.assert_array_equal(
        padded_model.latest_weights, ragged_model.latest_weights
    )


def test_linear_app_block_ragged_identical_stats(tmp_path, capsys):
    """--ingest block --wire ragged through the real app: identical stats
    to the padded block run."""
    import json

    import jax

    from tools.bench_suite import _status_json
    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.config import ConfArguments

    jax.devices()

    path = tmp_path / "tweets.jsonl"
    with open(path, "w") as fh:
        for s in synthetic(n=5 * 16, seed=23):
            fh.write(json.dumps(_status_json(s)) + "\n")

    def run(wire):
        conf = ConfArguments().parse([
            "--source", "replay", "--replayFile", str(path),
            "--seconds", "0", "--backend", "cpu", "--ingest", "block",
            "--batchBucket", "16", "--tokenBucket", "64",
            "--master", "local[1]", "--wire", wire,
        ])
        capsys.readouterr()
        totals = app.run(conf)
        return totals, [
            ln for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("count:")
        ]

    totals_p, lines_p = run("padded")
    totals_r, lines_r = run("ragged")
    # stream_seconds is wall-clock (r4, for the suite's startup split)
    totals_p.pop("stream_seconds", None); totals_r.pop("stream_seconds", None)
    assert totals_r == totals_p
    assert lines_r == lines_p
    # the small file arrives as ONE parsed block (a block item overshoots
    # the row cap by design), so one batch carries all rows
    assert len(lines_p) >= 1 and totals_p["count"] == 80


def test_ragged_matches_padded_logistic_sentiment_labels():
    """Config #3's exact shape: the logistic learner with C-lexicon
    sentiment labels (batch_label_fn reusing the featurizer's encode pass)
    through the ragged wire — bit-identical to the padded wire."""
    from twtml_tpu.features.sentiment import sentiment_label, sentiment_labels

    assert_identical_training(
        synthetic(n=96, seed=41),
        model_cls=StreamingLogisticRegressionWithSGD,
        feat_kw={
            "label_fn": sentiment_label,
            "batch_label_fn": sentiment_labels,
        },
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_ragged_fuzz_random_unicode(seed):
    """Seeded fuzz: random texts across codepoint planes — ASCII, Latin-1,
    CJK, astral (surrogate pairs), EMPTY strings, single chars, and long
    rows — must train bit-identically through both wires."""
    rng = np.random.default_rng(seed)
    pools = [
        lambda: chr(rng.integers(32, 127)),          # ASCII
        lambda: chr(rng.integers(0xC0, 0x17F)),      # Latin accents
        lambda: chr(rng.integers(0x4E00, 0x4F00)),   # CJK
        lambda: chr(rng.integers(0x1F300, 0x1F3FF)),  # astral emoji
    ]
    statuses = []
    for _ in range(64):
        kind = rng.integers(0, 8)
        if kind == 0:
            text = ""  # empty text row
        elif kind == 1:
            text = pools[rng.integers(0, 4)]()  # single char
        else:
            n_chars = int(rng.integers(2, 60))
            text = "".join(
                pools[rng.integers(0, 4)]() for _ in range(n_chars)
            )
        statuses.append(rt(text, label=int(rng.integers(100, 1001))))
    assert_identical_training(statuses, rows=16)
