"""--recycleAfterMb (r5, VERDICT r4 #7): the RSS watchdog's diagnosis made
actionable — crossing the ceiling checkpoints at the next weights-current
boundary and re-execs the process in place. The test forces a recycle with a
1 MB ceiling (always exceeded) and proves, from the run's own logs, that the
post-restart state is BIT-identical to the pre-exec save (matching state
CRCs), counters resume exactly, and the run completes."""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_replay(path, total=96):
    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    with open(path, "w") as fh:
        for s in SyntheticSource(
            total=total, seed=11, base_ms=1785320000000
        ).produce():
            fh.write(json.dumps(_status_json(s)) + "\n")


def test_auto_recycle_resumes_bit_identically(tmp_path):
    replay = tmp_path / "tweets.jsonl"
    _write_replay(replay)
    ckdir = tmp_path / "ck"
    closed = "http://127.0.0.1:9"
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        JAX_PLATFORMS="cpu",
        TWTML_RECYCLE_MAX="1",  # one recycle, then run to completion
        TWTML_RECYCLE_SAMPLE_EVERY="1",
    )
    proc = subprocess.run(
        [
            sys.executable, "-m", "twtml_tpu.apps.linear_regression",
            "--source", "replay", "--replayFile", str(replay),
            "--seconds", "0", "--backend", "cpu",
            "--batchBucket", "16", "--tokenBucket", "64",
            "--checkpointDir", str(ckdir),
            "--recycleAfterMb", "1",  # any real process exceeds 1 MB
            "--lightning", closed, "--twtweb", closed,
        ],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]

    recycles = re.findall(
        r"checkpointed at batch (\d+) \(count=(\d+), state crc ([0-9a-f]+)\)"
        r" and re-exec'ing", proc.stderr,
    )
    assert len(recycles) == 1, proc.stderr[-3000:]
    batch_r, count_r, crc_saved = (
        int(recycles[0][0]), int(recycles[0][1]), recycles[0][2],
    )

    resumes = re.findall(
        r"resumed from checkpoint step \d+ \(count=(\d+), state crc "
        r"([0-9a-f]+)\)", proc.stderr,
    )
    assert len(resumes) == 1, proc.stderr[-3000:]
    count_resumed, crc_restored = int(resumes[0][0]), resumes[0][1]

    # bit-identical post-restart state, exact counter resume
    assert crc_restored == crc_saved
    assert count_resumed == count_r

    # exact resume (ISSUE 19): the intake journal's boot replay
    # fast-forwards the re-exec'd process past every row the first life
    # journaled (SkipRowsSource) and re-ingests the post-cursor tail, so
    # the second life trains each row EXACTLY ONCE — the pre-journal
    # behavior re-read the whole file on top of the restored count
    boots = re.findall(
        r"journal: boot resume — (\d+) journaled row\(s\), (\d+) "
        r"fast-forwarded", proc.stderr,
    )
    assert len(boots) == 1, proc.stderr[-3000:]
    assert int(boots[0][0]) == int(boots[0][1])  # deterministic source
    stats = [
        ln for ln in proc.stdout.splitlines() if ln.startswith("count:")
    ]
    assert stats, proc.stdout[-2000:]
    final_count = int(re.findall(r"count: (\d+)", stats[-1])[0])
    assert final_count == 96

    from twtml_tpu.checkpoint import Checkpointer

    weights, meta = Checkpointer(str(ckdir)).restore()
    assert meta["count"] == final_count
    assert np.abs(np.asarray(weights)).sum() > 0


def test_recycle_refused_multihost(tmp_path, monkeypatch):
    """One host exec'ing away would desert the lockstep group — the flag
    must refuse loudly at startup in multi-host mode (apps/common)."""
    import jax
    import pytest

    from twtml_tpu.apps.common import ProcessRecycler
    from twtml_tpu.config import ConfArguments

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    conf = ConfArguments().parse([
        "--recycleAfterMb", "1024", "--checkpointDir", str(tmp_path),
    ])
    with pytest.raises(SystemExit, match="single-host"):
        ProcessRecycler(conf, ckpt=None, totals={"count": 0, "batches": 0})
