"""On-device featurization parity (ops/text_hash.py + UnitBatch path).

The device bigram hash must produce features bit-identical to the host
ground truth (features/hashing.py, itself MLlib-HashingTF-compatible —
MllibHelper.scala:42-56), and a learner fed UnitBatches must trace the exact
same weights/stats as one fed host-hashed FeatureBatches.
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from twtml_tpu.features import Featurizer, Status
from twtml_tpu.features.hashing import char_bigrams, hashing_tf_counts
from twtml_tpu.models import (
    StreamingLinearRegressionWithSGD,
    StreamingLogisticRegressionWithSGD,
)
from twtml_tpu.ops.sparse import densify_text
from twtml_tpu.ops.text_hash import hash_bigrams_device

DATA = os.path.join(os.path.dirname(__file__), "data", "tweets.jsonl")


@pytest.fixture()
def statuses():
    with open(DATA, encoding="utf-8") as fh:
        return [Status.from_json(json.loads(line)) for line in fh if line.strip()]


@pytest.fixture()
def feat():
    return Featurizer(now_ms=1785320000000)


def _status_with_text(text, count=250):
    return Status(
        text="RT wrapper",
        retweeted_status=Status(text=text, retweet_count=count),
    )


def _device_counts(text, num_features=1000):
    """Hash one text on device, return {idx: count} like hashing_tf_counts."""
    feat = Featurizer(now_ms=0)
    batch = feat.featurize_batch_units([_status_with_text(text)], pre_filtered=True)
    idx, val = hash_bigrams_device(
        jnp.asarray(batch.units), jnp.asarray(batch.length), num_features
    )
    idx, val = np.asarray(idx[0]), np.asarray(val[0])
    out: dict[int, float] = {}
    for i, v in zip(idx[val > 0], val[val > 0]):
        out[int(i)] = out.get(int(i), 0.0) + float(v)
    return out


@pytest.mark.parametrize(
    "text",
    [
        "breaking news from the summit today!",
        "",  # no terms
        "a",  # sliding(2) yields the 1-char string itself
        "ab",
        "aaaa",  # repeated bigram -> counts > 1
        "café résumé",  # accents (hashed raw by default)
        "fire \U0001f525\U0001f525 alert",  # astral: surrogate-pair windows
        "\U0001f600",  # lone astral char: two units, one bigram
        "BREAKING News!",  # ASCII case-folding happens in the C pad copy
        "Füße WALKING",  # non-ASCII text: Python lower(), C fold idempotent
        "İstanbul",  # U+0130 lowercases to 2 chars (length changes)
        "ΣΙΓΜΑ",  # uppercase outside ASCII entirely
    ],
)
def test_device_hash_matches_ground_truth(text):
    """Raw (unlowered) text through the Status-level API must hash exactly
    like the ground truth over the lowercased text."""
    expected = hashing_tf_counts(char_bigrams(text.lower()), 1000)
    assert _device_counts(text) == expected


def test_unit_batch_densifies_identically(statuses, feat):
    """Dense [B, F] matrices from both wire formats are equal elementwise."""
    host = feat.featurize_batch(statuses)
    dev = feat.featurize_batch_units(statuses)
    assert dev.units.dtype in (np.uint8, np.uint16)  # rules: TestCompactUnitsWire
    np.testing.assert_array_equal(host.mask, dev.mask)
    np.testing.assert_array_equal(host.label, dev.label)
    np.testing.assert_allclose(host.numeric, dev.numeric, rtol=1e-6)
    d_idx, d_val = hash_bigrams_device(
        jnp.asarray(dev.units), jnp.asarray(dev.length), 1000
    )
    dense_host = np.asarray(
        densify_text(
            jnp.asarray(host.token_idx, jnp.int32),
            jnp.asarray(host.token_val, jnp.float32),
            1000,
        )
    )
    dense_dev = np.asarray(densify_text(d_idx, d_val, 1000))
    np.testing.assert_array_equal(dense_host, dense_dev)


def test_unit_batch_row_and_unit_buckets(statuses, feat):
    batch = feat.featurize_batch_units(statuses, row_bucket=32, unit_bucket=128)
    assert batch.units.shape == (32, 128)
    assert batch.length.shape == (32,)
    n = int(batch.mask.sum())
    assert (batch.length[n:] == 0).all()


def test_unit_batch_empty():
    feat = Featurizer(now_ms=0)
    batch = feat.featurize_batch_units([])
    assert batch.mask.sum() == 0
    assert batch.units.shape[1] >= 2  # device bigram window needs L >= 2
    assert batch.units.dtype == np.uint8  # all-zero pad takes the u8 wire


class TestCompactUnitsWire:
    """uint8 units for byte-ranged batches (the transfer-bound wire
    optimization): dtype rule, feature parity, and training parity."""

    def test_ascii_batch_ships_uint8(self, feat):
        batch = feat.featurize_batch_units(
            [_status_with_text("plain ascii tweet!")], pre_filtered=True
        )
        assert batch.units.dtype == np.uint8

    def test_non_ascii_batch_ships_uint16(self, feat):
        # the gate is metadata (isascii), not a data sniff: even Latin-1
        # texts whose units would fit a byte keep the wide wire
        batch = feat.featurize_batch_units(
            [_status_with_text("café résumé")], pre_filtered=True  # é = 0xE9
        )
        assert batch.units.dtype == np.uint16
        batch = feat.featurize_batch_units(
            [_status_with_text("ΣΙΓΜΑ")], pre_filtered=True
        )
        assert batch.units.dtype == np.uint16

    def test_mixed_batch_ships_uint16(self, feat):
        batch = feat.featurize_batch_units(
            [_status_with_text("plain"), _status_with_text("emoji \U0001f600")],
            pre_filtered=True,
        )
        assert batch.units.dtype == np.uint16

    def test_block_path_dtype_follows_ascii_flags(self, feat):
        from twtml_tpu.features.blocks import merge_blocks
        from twtml_tpu.streaming.sources import BlockReplayFileSource

        merged = merge_blocks(list(BlockReplayFileSource(DATA).produce()))
        batch = feat.featurize_parsed_block(merged)
        want = np.uint8 if merged.ascii.all() else np.uint16
        assert batch.units.dtype == want

    def test_uint8_wire_trains_identically(self, feat, statuses):
        """Force both wire dtypes over the same tweets: identical weights."""
        batch = feat.featurize_batch_units(statuses)
        wide = batch._replace(units=batch.units.astype(np.uint16))
        a = StreamingLinearRegressionWithSGD(num_iterations=10)
        b = StreamingLinearRegressionWithSGD(num_iterations=10)
        out_a, out_b = a.step(batch), b.step(wide)
        assert float(out_a.mse) == float(out_b.mse)
        np.testing.assert_array_equal(a.latest_weights, b.latest_weights)


def test_unit_batch_accent_normalization():
    text = "Cafés"
    feat = Featurizer(now_ms=0, normalize_accents=True)
    batch = feat.featurize_batch_units(
        [_status_with_text(text)], pre_filtered=True
    )
    counts = hashing_tf_counts(char_bigrams("cafes"), 1000)
    idx, val = hash_bigrams_device(
        jnp.asarray(batch.units), jnp.asarray(batch.length), 1000
    )
    got: dict[int, float] = {}
    for i, v in zip(np.asarray(idx[0]), np.asarray(val[0])):
        if v > 0:
            got[int(i)] = got.get(int(i), 0.0) + float(v)
    assert got == counts


def test_linear_model_unit_batch_parity(statuses, feat):
    """Full fused step: UnitBatch and FeatureBatch runs produce identical
    weights and stats on the same stream of micro-batches."""
    host_model = StreamingLinearRegressionWithSGD(num_iterations=10)
    dev_model = StreamingLinearRegressionWithSGD(num_iterations=10)
    chunks = [statuses[:4], statuses[4:]]
    for chunk in chunks:
        out_h = host_model.step(feat.featurize_batch(chunk, row_bucket=8))
        out_d = dev_model.step(feat.featurize_batch_units(chunk, row_bucket=8))
        assert float(out_h.count) == float(out_d.count)
        np.testing.assert_allclose(
            float(out_h.mse), float(out_d.mse), rtol=1e-5
        )
    np.testing.assert_allclose(
        host_model.latest_weights, dev_model.latest_weights, rtol=1e-5, atol=1e-8
    )


def test_logistic_model_accepts_unit_batches(statuses):
    feat = Featurizer(now_ms=1785320000000, label_fn=lambda s: 1.0)
    model = StreamingLogisticRegressionWithSGD(num_iterations=5)
    out = model.step(feat.featurize_batch_units(statuses))
    assert float(out.count) == 6.0  # the filtrate-passing fixtures


@pytest.mark.parametrize("layout", ["data", "data_model"])
def test_parallel_model_unit_batch_parity(statuses, feat, layout):
    """Mesh-sharded steps (both layouts) fed UnitBatches match the
    single-device host-hashed run."""
    from twtml_tpu.parallel import ParallelSGDModel, make_mesh

    if layout == "data":
        mesh = make_mesh(num_data=4)
    else:
        mesh = make_mesh(num_data=2, num_model=2)
    ref = StreamingLinearRegressionWithSGD(num_iterations=10)
    par = ParallelSGDModel(mesh, num_iterations=10, step_size=0.005)
    host_b = feat.featurize_batch(statuses, row_bucket=8)
    unit_b = feat.featurize_batch_units(statuses, row_bucket=8)
    out_ref = ref.step(host_b)
    out_par = par.step(unit_b)
    assert float(out_ref.count) == float(out_par.count)
    np.testing.assert_allclose(float(out_ref.mse), float(out_par.mse), rtol=1e-5)
    np.testing.assert_allclose(
        ref.latest_weights, par.latest_weights, rtol=1e-4, atol=1e-7
    )


def test_sparse_path_accepts_unit_batches(statuses, feat):
    """2^18-dim config (BASELINE #4) rides the gather/scatter path; device
    hashing must feed it the same features as host hashing."""
    f = 2**18
    big = Featurizer(num_text_features=f, now_ms=1785320000000)
    m_host = StreamingLinearRegressionWithSGD(num_text_features=f, num_iterations=5)
    m_dev = StreamingLinearRegressionWithSGD(num_text_features=f, num_iterations=5)
    m_host.step(big.featurize_batch(statuses))
    m_dev.step(big.featurize_batch_units(statuses))
    np.testing.assert_allclose(
        m_host.latest_weights, m_dev.latest_weights, rtol=1e-5, atol=1e-8
    )


def test_unit_batch_numpy_fallback_case_folds(monkeypatch):
    """Without the C library the numpy pad path must fold ASCII case the
    same way (C folds during the copy; numpy folds after the gather)."""
    from twtml_tpu.features import native

    monkeypatch.setattr(native, "pad_units", lambda *a, **k: None)
    assert _device_counts("BREAKING News!") == hashing_tf_counts(
        char_bigrams("breaking news!"), 1000
    )
