"""Native (C++) featurizer parity: the ctypes fast path must produce exactly
the same hashed term-frequency sets as the pure-Python ground truth
(features/hashing.py), including emoji surrogate pairs, collisions, and
padding layout."""

import json
import os

import numpy as np
import pytest

from twtml_tpu.features import Featurizer, Status, native

DATA = os.path.join(os.path.dirname(__file__), "data", "tweets.jsonl")

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native featurizer unavailable (no g++?)"
)


def rows_as_dicts(batch):
    out = []
    for i in range(batch.token_idx.shape[0]):
        row = {}
        for j in range(batch.token_idx.shape[1]):
            if batch.token_val[i, j] != 0:
                row[int(batch.token_idx[i, j])] = float(batch.token_val[i, j])
        out.append(row)
    return out


@pytest.fixture()
def statuses():
    with open(DATA, encoding="utf-8") as fh:
        return [Status.from_json(json.loads(line)) for line in fh if line.strip()]


def test_native_matches_python_on_fixture(statuses):
    feat = Featurizer(now_ms=1785320000000)
    fast = feat._featurize_batch_native(
        [s for s in statuses if feat.filtrate(s)], 0, 0
    )
    assert fast is not None
    # force the python path by pretending native is unavailable
    keep = [s for s in statuses if feat.filtrate(s)]
    from twtml_tpu.features.batch import pad_feature_batch

    slow = pad_feature_batch([feat.featurize(s) for s in keep])
    fast_rows = rows_as_dicts(fast)
    slow_rows = rows_as_dicts(slow)
    for i in range(len(keep)):
        assert fast_rows[i] == slow_rows[i], f"row {i} diverged"
    np.testing.assert_allclose(fast.numeric, slow.numeric, rtol=1e-6)
    np.testing.assert_array_equal(fast.label, slow.label)
    np.testing.assert_array_equal(fast.mask, slow.mask)


def test_native_handles_emoji_and_short_texts():
    feat = Featurizer(now_ms=0)
    cases = ["😀", "a", "", "héllo 😀🚀 wörld", "aa" * 139]
    keep = [
        Status(retweeted_status=Status(text=t, retweet_count=500)) for t in cases
    ]
    fast = feat._featurize_batch_native(keep, 0, 0)
    from twtml_tpu.features.batch import pad_feature_batch

    slow = pad_feature_batch([feat.featurize(s) for s in keep])
    assert rows_as_dicts(fast)[: len(cases)] == rows_as_dicts(slow)[: len(cases)]


def test_collision_accumulation_tiny_mod():
    feat = Featurizer(num_text_features=2, now_ms=0)
    keep = [Status(retweeted_status=Status(text="abcdef", retweet_count=500))]
    fast = feat._featurize_batch_native(keep, 0, 0)
    from twtml_tpu.features.batch import pad_feature_batch

    slow = pad_feature_batch([feat.featurize(s) for s in keep])
    assert rows_as_dicts(fast)[0] == rows_as_dicts(slow)[0]
    assert sum(rows_as_dicts(fast)[0].values()) == 5.0  # 5 bigrams total


def test_uncommon_configs_fall_back():
    feat = Featurizer(normalize_accents=True, now_ms=0)
    assert feat._featurize_batch_native([], 0, 0) is None


def test_over_1024_distinct_terms_falls_back_not_hangs():
    """A tweet with >1024 distinct bigrams must overflow the C scratch table
    gracefully (fallback), never spin (regression for the unbounded probe
    loop)."""
    text = "".join(chr(0x4E00 + i) for i in range(1200))  # 1199 distinct bigrams
    feat = Featurizer(num_text_features=100000, now_ms=0)
    s = Status(retweeted_status=Status(text=text, retweet_count=500))
    assert feat._featurize_batch_native([s], 0, 0) is None  # signals fallback
    # and the public API still yields correct (python-path) features
    batch = feat.featurize_batch([s], pre_filtered=True)
    assert batch.num_valid == 1
    assert int((batch.token_val[0] > 0).sum()) == 1199


def test_multithreaded_path_matches_python(monkeypatch):
    """Exercise the row-parallel C path (n_threads>1 needs >=512 rows to
    clear the per-thread row minimum) against the Python ground truth —
    partitioning, per-thread scratch tables, and slot resets included.
    Mixes empty, single-char, emoji, and long rows across the partitions."""
    monkeypatch.setenv("TWTML_NATIVE_THREADS", "4")
    texts = ["", "a", "😀", "héllo 😀🚀 wörld", "the quick brown fox", "ab" * 120]
    keep = [
        Status(retweeted_status=Status(text=texts[i % len(texts)] + str(i), retweet_count=500))
        for i in range(1024)
    ]
    feat = Featurizer(now_ms=0)
    fast = feat._featurize_batch_native(keep, 0, 0)
    assert fast is not None
    from twtml_tpu.features.batch import pad_feature_batch

    slow = pad_feature_batch([feat.featurize(s) for s in keep])
    assert rows_as_dicts(fast)[: len(keep)] == rows_as_dicts(slow)[: len(keep)]


def test_thread_env_non_integer_falls_back_to_auto(monkeypatch):
    monkeypatch.setenv("TWTML_NATIVE_THREADS", "auto")
    feat = Featurizer(now_ms=0)
    s = Status(retweeted_status=Status(text="hello world", retweet_count=500))
    batch = feat.featurize_batch([s], pre_filtered=True)  # must not raise
    assert batch.num_valid == 1


def test_custom_label_fn_uses_native_hashing_with_python_labels():
    from twtml_tpu.features.sentiment import sentiment_label

    feat = Featurizer(now_ms=0)
    feat.label_fn = sentiment_label
    keep = [
        Status(retweeted_status=Status(text=t, retweet_count=500))
        for t in ("i love this great day", "terrible awful broken mess", "neutral words only")
    ]
    fast = feat._featurize_batch_native(keep, 0, 0)
    assert fast is not None  # label_fn no longer forces the python path
    from twtml_tpu.features.batch import pad_feature_batch

    slow = pad_feature_batch([feat.featurize(s) for s in keep])
    assert rows_as_dicts(fast)[:3] == rows_as_dicts(slow)[:3]
    np.testing.assert_array_equal(fast.label[:3], slow.label[:3])
    assert list(fast.label[:3]) == [1.0, 0.0, 1.0]
