"""Implementation-independent literals for the MLlib GradientDescent
semantics (VERDICT r3 #6).

The differential oracle in tests/test_sgd_models.py is independent CODE, but
code beside the implementation can share a misreading of the spec. These
tests pin the parity-critical update rule — stepSize/√i decay (1-indexed),
SquaredL2Updater pre-scale, zero-sample skip, convergence freeze
(GradientDescent.runMiniBatchSGD, SURVEY.md §3.3) — to HAND-COMPUTED
trajectories: tiny integer batches, every iteration's arithmetic written
out in the comments, expected weights as decimal literals. Each literal is
checked against all three formulations of the loop (dense matmul, sparse
gather/scatter, Gram dual — models/sgd.py, ops/gram.py): a bug shared by
an oracle and the implementation cannot survive a hand-derived constant.

Batch layout: x rows are unit vectors over 2 text features; the 4 numeric
features are zero except where a test says otherwise; padded token slots
carry (idx=0, val=0) per the batch contract.
"""

import numpy as np

import jax.numpy as jnp

from twtml_tpu.features.batch import NUM_NUMBER_FEATURES, FeatureBatch
from twtml_tpu.models.sgd import make_sgd_train_step

F_TEXT = 2
DIM = F_TEXT + NUM_NUMBER_FEATURES

# e0/e1 rows: row i has a single token occurrence of feature i (val 1.0),
# second slot padded
TOKEN_IDX = np.array([[0, 0], [1, 0]], np.int32)
TOKEN_VAL = np.array([[1.0, 0.0], [1.0, 0.0]], np.float32)


def two_row_batch(labels, mask=(1.0, 1.0)):
    return FeatureBatch(
        TOKEN_IDX,
        TOKEN_VAL,
        np.zeros((2, NUM_NUMBER_FEATURES), np.float32),
        np.asarray(labels, np.float32),
        np.asarray(mask, np.float32),
    )


def all_formulations(**kw):
    """The same semantics through every loop formulation in the framework."""
    kw.setdefault("num_text_features", F_TEXT)
    kw.setdefault("mini_batch_fraction", 1.0)
    kw.setdefault("convergence_tol", 0.0)
    return {
        "dense": make_sgd_train_step(use_sparse=False, **kw),
        "scatter": make_sgd_train_step(use_sparse=True, use_gram=False, **kw),
        "gram": make_sgd_train_step(use_sparse=True, use_gram=True, **kw),
    }


def assert_all_hit(steps, w0, batch, expected, rtol=1e-6, atol=1e-6):
    for name, step in steps.items():
        w1, _ = step(jnp.asarray(w0, jnp.float32), batch)
        np.testing.assert_allclose(
            np.asarray(w1), expected, rtol=rtol, atol=atol,
            err_msg=f"formulation {name!r} missed the hand-computed literal",
        )


def test_sqrt_decay_two_iterations_literal():
    """stepSize/√i, 1-indexed, from w0 = 0; labels y = (2, 4), stepSize 1.

    it=1: η = 1/√1 = 1. raw = (0, 0); residuals r = (0−2, 0−4) = (−2, −4).
          grad_sum = (−2, −4); count = 2 ⇒ grad/denom = (−1, −2).
          w = 0 − 1·(−1, −2) = (1, 2).
    it=2: η = 1/√2. raw = (1, 2); r = (−1, −2); grad/denom = (−1/2, −1).
          w = (1 + 1/(2√2), 2 + 1/√2).
    Literals: 1 + 1/(2√2) = 1.3535533905932737…, 2 + 1/√2 = 2.7071067811865475…
    (a 1-indexing bug would give η = 1/√2, 1/√3 → (1.1153.., 2.2306..)·2 — far
    outside tolerance; a 0-indexed-η=∞ bug would NaN).
    """
    steps = all_formulations(num_iterations=2, step_size=1.0)
    expected = np.array(
        [1.3535533905932737, 2.7071067811865475, 0, 0, 0, 0], np.float64
    )
    assert_all_hit(steps, np.zeros(DIM), two_row_batch((2.0, 4.0)), expected)


def test_l2_pre_scale_one_iteration_literal():
    """SquaredL2Updater: w ← w·(1 − η·λ) − η·g/n, λ = 0.5, stepSize 1,
    w0 = ones (INCLUDING the numeric weights the batch never touches).

    it=1: η = 1. raw = (1, 1); y = (2, 4) ⇒ r = (−1, −3); grad/denom =
          (−1/2, −3/2) on the two text dims, 0 on the numeric dims.
          text:    w = 1·(1 − 0.5) + (0.5, 1.5) = (1.0, 2.0)
          numeric: w = 1·(1 − 0.5) − 0       = 0.5   ← the pre-scale hits
          untouched weights too (the lazy-c dual path must match this).
    """
    steps = all_formulations(num_iterations=1, step_size=1.0, l2_reg=0.5)
    expected = np.array([1.0, 2.0, 0.5, 0.5, 0.5, 0.5], np.float64)
    assert_all_hit(steps, np.ones(DIM), two_row_batch((2.0, 4.0)), expected)


def test_l2_stationary_point_two_iterations_literal():
    """At w = (1, 2) with y = (2, 4), λ = 0.5: residuals r = (−1, −2), so
    grad/denom = (−1/2, −1) = −λ·w exactly — the L2-regularized stationary
    point (∇½mse + λw = 0). A second iteration at any η must leave the
    touched weights EXACTLY fixed while the untouched numeric weights keep
    shrinking by (1 − η·λ):

    it=2: η = 1/√2.  text:    w = w·(1 − η/2) + η·(1/2, 1) = (1, 2)  (exact)
                     numeric: w = 0.5·(1 − 1/(2√2)) = 0.32322330470336313
    """
    steps = all_formulations(num_iterations=2, step_size=1.0, l2_reg=0.5)
    expected = np.array(
        [1.0, 2.0] + [0.32322330470336313] * 4, np.float64
    )
    assert_all_hit(steps, np.ones(DIM), two_row_batch((2.0, 4.0)), expected)


def test_zero_sample_iteration_skips_literal():
    """MLlib: an iteration that samples zero points leaves weights UNCHANGED
    — no L2 shrink, no NaN from the 0-count denominator. With every row
    masked out, all 3 iterations must be exact no-ops on a nonzero w0
    (λ = 0.5 would shrink w if the skip were broken)."""
    steps = all_formulations(num_iterations=3, step_size=1.0, l2_reg=0.5)
    w0 = np.array([1.0, -2.0, 3.0, 4.0, 0.25, -0.5])
    assert_all_hit(
        steps, w0, two_row_batch((2.0, 4.0), mask=(0.0, 0.0)), w0, rtol=0, atol=0
    )


def test_convergence_freeze_literal():
    """Convergence test ‖w_i − w_{i−1}‖ < tol·max(‖w_i‖, 1), then FREEZE.
    One row (x = e0, y = 2), stepSize 0.5, tol 0.4, 3 iterations, w0 = 0:

    it=1: η = 0.5. r = −2 ⇒ w = (1). Δ = 1, ‖w‖ = 1: 1 < 0.4? no.
    it=2: η = 0.5/√2. r = 1 − 2 = −1 ⇒ w = 1 + 0.5/√2 = 1.3535533905932737.
          Δ = 0.3535533…, tol·‖w‖ = 0.4·1.3535533… = 0.5414213…: CONVERGED
          (the it=2 update is still applied; freeze starts NEXT iteration).
    it=3: frozen — w stays 1.3535533905932737. Without the freeze it would
          move to w + (0.5/√3)·(2 − w) = 1.5401664525721208… (checked ≠).
    """
    frozen = all_formulations(
        num_iterations=3, step_size=0.5, convergence_tol=0.4
    )
    batch = FeatureBatch(
        np.array([[0, 0]], np.int32),
        np.array([[1.0, 0.0]], np.float32),
        np.zeros((1, NUM_NUMBER_FEATURES), np.float32),
        np.array([2.0], np.float32),
        np.array([1.0], np.float32),
    )
    expected = np.array([1.3535533905932737, 0, 0, 0, 0, 0], np.float64)
    assert_all_hit(frozen, np.zeros(DIM), batch, expected)
    # and the freeze is what held it there: tol=0 runs through to it=3
    free = all_formulations(num_iterations=3, step_size=0.5)
    unfrozen = np.array([1.5401664525721208, 0, 0, 0, 0, 0], np.float64)
    assert_all_hit(free, np.zeros(DIM), batch, unfrozen, rtol=1e-5, atol=1e-6)
