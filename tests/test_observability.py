"""Fleet observability plane (ISSUE 5): the per-host telemetry sideband,
the lockstep straggler attributor, and the crash flight recorder.

The hard constraints are asserted the way PR 1/PR 4 asserted theirs:
the sideband path issues ZERO added host fetches (jax.device_get counted
end to end over a real lockstep run) and ZERO added collectives (exactly
one cadence allgather per tick — process_allgather counted). The flight
recorder's bundle must be parseable by tools/postmortem_report.py (exit 0;
malformed bundles exit 2), and the CI post-mortem smoke drives a chaos run
into a sentinel abort and renders the bundle it leaves behind.
"""

import json
import os
import signal

import numpy as np
import pytest

from tools import postmortem_report
from twtml_tpu.telemetry import blackbox as blackbox_mod
from twtml_tpu.telemetry import metrics as metrics_mod
from twtml_tpu.telemetry import sideband as sideband_mod
from twtml_tpu.telemetry.straggler import StragglerAttributor

BASE_MS = 1785320000000


@pytest.fixture(autouse=True)
def clean_state():
    metrics_mod.reset_for_tests()
    sideband_mod.reset_for_tests()
    blackbox_mod.uninstall()
    yield
    blackbox_mod.uninstall()
    sideband_mod.reset_for_tests()
    metrics_mod.reset_for_tests()


# ---------------------------------------------------------------------------
# stage clock + sideband collector


def test_stage_clock_accumulates_and_disables():
    sideband_mod.record_stage("fetch", 0.25)
    sideband_mod.record_stage("fetch", 0.25)
    sideband_mod.record_stage("dispatch", 0.1)
    assert sideband_mod.stage_seconds()["fetch"] == pytest.approx(0.5)
    sideband_mod.set_stage_clock(False)
    sideband_mod.record_stage("fetch", 9.0)  # the bench control arm's no-op
    assert sideband_mod.stage_seconds()["fetch"] == pytest.approx(0.5)
    sideband_mod.set_stage_clock(True)


def test_collector_ships_deltas_not_totals():
    c = sideband_mod.SidebandCollector()
    sideband_mod.record_stage("featurize", 0.2)
    v1 = c.collect()
    assert v1.shape == (sideband_mod.WIDTH,)
    assert v1.dtype == np.float64
    i = sideband_mod.FIELDS.index("featurize_ms")
    assert v1[i] == pytest.approx(200.0)
    # second tick with no new featurize work: the DELTA is zero
    v2 = c.collect()
    assert v2[i] == 0.0
    # registry-backed fields ride along
    metrics_mod.get_registry().gauge("ingest.queue_rows").set(4096)
    metrics_mod.get_registry().counter("ingest.rows_shed").inc(7)
    v3 = c.collect(rollbacks=2)
    assert v3[sideband_mod.FIELDS.index("queue_rows")] == 4096
    assert v3[sideband_mod.FIELDS.index("rows_shed")] == 7
    assert v3[sideband_mod.FIELDS.index("rollbacks")] == 2
    assert v3[sideband_mod.FIELDS.index("tick_prep_ms")] >= 0


def test_collector_never_ships_nonfinite():
    c = sideband_mod.SidebandCollector()
    sideband_mod.record_stage("fetch", float("nan"))
    v = c.collect()
    assert np.isfinite(v).all()


# ---------------------------------------------------------------------------
# straggler attribution


def _matrix(prep, **stages):
    """[hosts, WIDTH] matrix with per-host tick_prep and named stage ms."""
    m = np.zeros((len(prep), sideband_mod.WIDTH))
    m[:, sideband_mod.FIELDS.index("tick_prep_ms")] = prep
    for name, vals in stages.items():
        m[:, sideband_mod.FIELDS.index(name)] = vals
    return m


def test_straggler_names_host_and_ladder_stage():
    a = StragglerAttributor()
    # host 1 gates every tick, its dispatch (upload) dominating
    v = a.observe(_matrix(
        [10.0, 160.0],
        dispatch_ms=[2.0, 140.0], featurize_ms=[5.0, 6.0],
        fetch_ms=[2.0, 2.0],
    ))
    assert v["host"] == 1
    assert v["stage"] == "upload"
    assert v["skew_ms"] == pytest.approx(150.0)
    reg = metrics_mod.get_registry()
    assert reg.gauge("lockstep.straggler_host").snapshot() == 1
    assert reg.gauge("lockstep.tick_skew_ms").snapshot() == pytest.approx(150.0)
    assert reg.counter("straggler.upload.ticks").snapshot() == 1


def test_straggler_quiet_below_skew_floor():
    a = StragglerAttributor()
    v = a.observe(_matrix([10.0, 11.0], fetch_ms=[8.0, 8.0]))
    assert v["host"] == -1 and v["stage"] == ""
    assert metrics_mod.get_registry().gauge(
        "lockstep.straggler_host"
    ).snapshot() == -1


def test_straggler_falls_back_to_device_when_host_stages_explain_nothing():
    a = StragglerAttributor()
    # host 0 gates by 400ms but its host-side stages account for ~1% of the
    # tick: the time went to the device step / collective interior
    v = a.observe(_matrix([500.0, 100.0], dispatch_ms=[5.0, 4.0]))
    assert v["host"] == 0
    assert v["stage"] == "device"


def test_straggler_deviation_beats_absolute_once_history_exists():
    a = StragglerAttributor(min_history=4)
    # steady state: host 1 always has big (legitimate) fetch times
    for _ in range(8):
        a.observe(_matrix(
            [10.0, 12.0], fetch_ms=[50.0, 50.0], featurize_ms=[5.0, 5.0]
        ))
    # now featurize BLOWS UP on host 1 — deviation ranks it above the
    # absolutely-larger-but-unchanged fetch column
    v = a.observe(_matrix(
        [10.0, 90.0], fetch_ms=[50.0, 52.0], featurize_ms=[5.0, 70.0]
    ))
    assert v["host"] == 1
    assert v["stage"] == "featurize"


def test_lockstep_telemetry_publishes_hosts_view():
    tele = sideband_mod.LockstepTelemetry(0, 2)
    m = _matrix([10.0, 200.0], dispatch_ms=[2.0, 150.0])
    tele.ingest(m)
    view = sideband_mod.last_hosts()
    assert view is not None
    assert len(view["hosts"]) == 2
    assert view["hosts"][1]["tick_prep_ms"] == pytest.approx(200.0)
    assert view["straggler"] == 1
    assert view["stage"] == "upload"
    assert metrics_mod.get_registry().counter("lockstep.ticks").snapshot() == 1


# ---------------------------------------------------------------------------
# THE acceptance constraint: zero added fetches, zero added collectives —
# a real lockstep run with the sideband riding the one cadence allgather


def test_sideband_adds_no_fetches_and_no_collectives(monkeypatch):
    import jax
    from jax.experimental import multihost_utils

    from twtml_tpu.apps.common import FetchPipeline
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.context import StreamingContext
    from twtml_tpu.streaming.sources import SyntheticSource

    jax.devices()  # lock the conftest backend
    calls = {"allgather": 0, "get": 0}
    real_ag = multihost_utils.process_allgather

    def counting_ag(arr):
        calls["allgather"] += 1
        return real_ag(arr)

    monkeypatch.setattr(multihost_utils, "process_allgather", counting_ag)
    real_get = jax.device_get

    def counting_get(x):
        calls["get"] += 1
        return real_get(x)

    monkeypatch.setattr(jax, "device_get", counting_get)

    ssc = StreamingContext(batch_interval=0)
    stream = ssc.source_stream(
        SyntheticSource(total=64, seed=7, base_ms=BASE_MS),
        Featurizer(now_ms=BASE_MS),
        row_bucket=16, token_bucket=64, device_hash=True,
    )
    model = StreamingLinearRegressionWithSGD(num_iterations=2)
    pipe = FetchPipeline(
        model, lambda out, b, t, at_boundary: None, deterministic=True
    )
    stream.foreach_batch(pipe.on_batch)
    ssc.start(lockstep=True)
    assert ssc.await_termination(timeout=120)
    ssc.stop()
    pipe.flush()
    assert not ssc.failed
    assert ssc.batches_processed >= 4

    reg = metrics_mod.get_registry().snapshot()
    ticks = reg["counters"]["lockstep.ticks"]
    # ZERO added collectives: exactly ONE allgather per lockstep tick —
    # the sideband rides it, it never adds one
    assert calls["allgather"] == ticks
    # ZERO added host fetches: one per dispatched batch (FetchPipeline's
    # contract), none from the sideband/straggler/collector path
    assert calls["get"] == ssc.batches_processed
    assert reg["counters"]["fetch.count"] == ssc.batches_processed
    # and the hosts[] view materialized (single host, never "gating")
    view = sideband_mod.last_hosts()
    assert view is not None and len(view["hosts"]) == 1
    assert view["straggler"] == -1


# ---------------------------------------------------------------------------
# flight recorder: ring, notes, bundle, dump, SIGTERM


def test_ring_is_bounded_and_counts_drops(tmp_path):
    rec = blackbox_mod.install(
        config={"x": 1}, out_dir=str(tmp_path), capacity=8
    )
    for i in range(20):
        rec.record("tick", i=i)
    bundle = rec.bundle("test")
    assert len(bundle["events"]) == 8
    assert bundle["events"][-1]["i"] == 19  # newest survive
    assert bundle["events_dropped"] == 12
    for key in postmortem_report.REQUIRED_KEYS:
        assert key in bundle


def test_notes_survive_ring_churn(tmp_path):
    rec = blackbox_mod.install(out_dir=str(tmp_path), capacity=4)
    blackbox_mod.note("last_checkpoint", {"step": 12, "count": 24576})
    for i in range(64):
        rec.record("noise", i=i)
    assert rec.bundle("t")["notes"]["last_checkpoint"]["step"] == 12


def test_dump_is_single_shot_until_forced(tmp_path):
    rec = blackbox_mod.install(out_dir=str(tmp_path))
    p1 = rec.dump("first")
    p2 = rec.dump("second")  # no-op: one bundle per failure
    assert p1 == p2
    doc = json.load(open(p1))
    assert doc["reason"] == "first"
    p3 = rec.dump("forced", force=True)
    assert json.load(open(p3))["reason"] == "forced"


def test_request_abort_funnel_dumps_bundle(tmp_path):
    from twtml_tpu.streaming.context import StreamingContext

    blackbox_mod.install(config={"app": "t"}, out_dir=str(tmp_path))
    ssc = StreamingContext()
    ssc.request_abort("unit-test abort")
    assert ssc.failed
    path = blackbox_mod.last_dump_path()
    assert path and os.path.exists(path)
    doc = postmortem_report.load_bundle(path)
    assert doc["reason"] == "unit-test abort"
    assert any(e["kind"] == "abort" for e in doc["events"])
    assert postmortem_report.main([path]) == 0


def test_trace_spans_ride_the_ring(tmp_path):
    from twtml_tpu.telemetry import trace as trace_mod

    rec = blackbox_mod.install(out_dir=str(tmp_path))
    tr = trace_mod.install(str(tmp_path / "t.trace"))
    with tr.span("featurize", rows=16):
        pass
    tr.instant("health_phase", phase="degraded")
    trace_mod.uninstall()
    kinds = [e["kind"] for e in rec.bundle("t")["events"]]
    assert "span" in kinds and "instant" in kinds
    span = [e for e in rec.bundle("t")["events"] if e["kind"] == "span"][0]
    assert span["name"] == "featurize" and span["rows"] == 16


def test_sigterm_handler_dumps_and_chains(tmp_path):
    rec = blackbox_mod.install(out_dir=str(tmp_path))
    chained = []
    blackbox_mod._on_sigterm(
        signal.SIGTERM, None, _prev=lambda s, f: chained.append(s)
    )
    assert chained == [signal.SIGTERM]
    path = rec.last_dump_path
    assert path and json.load(open(path))["reason"] == "SIGTERM"


def test_module_level_record_is_noop_without_recorder():
    blackbox_mod.uninstall()
    blackbox_mod.record("anything", x=1)  # must not raise
    blackbox_mod.note("k", "v")
    assert blackbox_mod.abort_dump("r") is None
    assert blackbox_mod.dump("r") is None


# ---------------------------------------------------------------------------
# postmortem_report as a CHECK (CI and chaos_soak gate on its exit status)


def test_postmortem_report_exit_codes(tmp_path):
    rec = blackbox_mod.install(config={"a": 1}, out_dir=str(tmp_path))
    rec.record("chaos", target="fetch", action="delay", call=3)
    good = rec.dump("test bundle")
    assert postmortem_report.main([good]) == 0
    assert postmortem_report.main([good, "--json"]) == 0

    bad = tmp_path / "bad.json"
    bad.write_text("not json {")
    assert postmortem_report.main([str(bad)]) == 2
    empty = tmp_path / "empty.json"
    empty.write_text("")
    assert postmortem_report.main([str(empty)]) == 2
    not_bundle = tmp_path / "nb.json"
    not_bundle.write_text(json.dumps({"kind": "something-else"}))
    assert postmortem_report.main([str(not_bundle)]) == 2
    missing_keys = tmp_path / "mk.json"
    doc = json.load(open(good))
    del doc["events"]
    missing_keys.write_text(json.dumps(doc))
    assert postmortem_report.main([str(missing_keys)]) == 2
    assert postmortem_report.main([str(tmp_path / "absent.json")]) == 2


def test_postmortem_report_summary_contents(tmp_path):
    rec = blackbox_mod.install(
        config={"_appName": "twtml-test"}, out_dir=str(tmp_path)
    )
    blackbox_mod.note("last_checkpoint", {"step": 8, "count": 16384})
    rec.record("fetch_retry", attempt=1, why="timeout")
    rec.record("fetch_abort", attempts=4, why="timeout")
    sideband_mod.publish_hosts({
        "hosts": [{"host": 0}, {"host": 1}],
        "straggler": 1, "stage": "upload", "skew_ms": 140.0,
    })
    path = rec.dump("fetch watchdog exhausted")
    s = postmortem_report.summarize(postmortem_report.load_bundle(path))
    assert s["reason"] == "fetch watchdog exhausted"
    assert s["checkpoint"] == {"step": 8, "count": 16384}
    assert s["event_kinds"] == {"fetch_retry": 1, "fetch_abort": 1}
    assert s["straggler"] == {"host": 1, "stage": "upload", "skew_ms": 140.0}
    text = postmortem_report.render(s)
    assert "fetch watchdog exhausted" in text
    assert "host 1 · upload" in text


# ---------------------------------------------------------------------------
# CI post-mortem smoke: a chaos run dies on the sentinel's rollback budget
# and leaves a bundle the report renders — end to end through the real app


def _write_replay(tmp_path, n):
    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    path = tmp_path / "tweets.jsonl"
    with open(path, "w") as fh:
        for s in SyntheticSource(total=n, seed=7, base_ms=BASE_MS).produce():
            fh.write(json.dumps(_status_json(s)) + "\n")
    return path


def test_postmortem_smoke_killed_chaos_run_leaves_wellformed_bundle(tmp_path):
    import jax

    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.config import ConfArguments
    from twtml_tpu.streaming import faults

    jax.devices()
    replay = _write_replay(tmp_path, 4 * 16)
    conf = ConfArguments().parse([
        "--source", "replay", "--replayFile", str(replay),
        "--seconds", "0", "--backend", "cpu", "--master", "local[1]",
        "--batchBucket", "16", "--tokenBucket", "64",
        "--checkpointDir", str(tmp_path / "ck"), "--checkpointEvery", "1",
        "--chaos", "source.nan@2",
        "--sentinelRollbacks", "1", "--sentinelWindow", "8",
        "--lightning", "http://127.0.0.1:9", "--twtweb", "http://127.0.0.1:9",
    ])
    try:
        with pytest.raises(RuntimeError):
            app.run(conf)
    finally:
        faults.uninstall_chaos()
    path = blackbox_mod.last_dump_path()
    assert path and os.path.exists(path)
    # the bundle lands NEXT TO the checkpoint dir
    assert os.path.dirname(path) == str(tmp_path)
    assert postmortem_report.main([path]) == 0
    doc = postmortem_report.load_bundle(path)
    kinds = {e["kind"] for e in doc["events"]}
    # the way down is on record: the chaos rule fired, the sentinel rolled
    # back, the budget abort triggered, the funnel dumped
    assert {"chaos", "sentinel_rollback", "sentinel_abort", "abort"} <= kinds
    assert doc["notes"]["last_checkpoint"]["step"] >= 1
    assert doc["config"]["chaos"] == "source.nan@2"
