"""Transport chaos harness (streaming/faults.ChaosInjector + ``--chaos``)
and the end-to-end behavior it exists to prove: a run SURVIVES injected
fetch/dispatch faults and publish outages (retries + breaker, no hang, no
lost rows), and a run whose transport wedges for good aborts CLEANLY with
a checkpoint a restarted run resumes from — the ISSUE 2 acceptance
criteria."""

import json
import time

import numpy as np
import pytest

from twtml_tpu.config import ConfArguments
from twtml_tpu.streaming import faults
from twtml_tpu.streaming.faults import ChaosInjector, InjectedFault
from twtml_tpu.streaming.sources import SyntheticSource
from twtml_tpu.telemetry import metrics as _metrics


@pytest.fixture(autouse=True)
def clean_chaos():
    _metrics.reset_for_tests()
    faults.uninstall_chaos()
    yield
    faults.uninstall_chaos()
    _metrics.reset_for_tests()


def _fires(inj, target, calls):
    out = []
    for _ in range(calls):
        try:
            inj.perturb(target)
            out.append(False)
        except InjectedFault:
            out.append(True)
    return out


# -- spec parsing + injection semantics --------------------------------------

def test_every_nth_trigger_is_deterministic():
    fired = _fires(ChaosInjector("fetch:error@3"), "fetch", 9)
    assert [i + 1 for i, f in enumerate(fired) if f] == [3, 6, 9]


def test_from_trigger_is_a_permanent_outage():
    fired = _fires(ChaosInjector("step:error@from4"), "step", 6)
    assert fired == [False, False, False, True, True, True]


def test_delay_rule_sleeps_and_counts():
    inj = ChaosInjector("fetch:delay=0.05@2")
    t0 = time.perf_counter()
    for _ in range(4):
        inj.perturb("fetch")  # delays on calls 2 and 4
    assert time.perf_counter() - t0 >= 0.1
    reg = _metrics.get_registry()
    assert reg.counter("chaos.fetch.delays").snapshot() == 2
    assert reg.counter("chaos.injected").snapshot() == 2


def test_probability_trigger_is_seeded_deterministic():
    spec = "web:error@p0.5,seed=9"
    a = _fires(ChaosInjector(spec), "web", 50)
    b = _fires(ChaosInjector(spec), "web", 50)
    assert a == b
    assert 5 < sum(a) < 45  # actually probabilistic, not all-or-nothing


def test_targets_are_independent():
    inj = ChaosInjector("fetch:error@1")
    inj.perturb("web")  # no web rules: untouched
    inj.perturb("step")
    with pytest.raises(InjectedFault):
        inj.perturb("fetch")


@pytest.mark.parametrize("bad", [
    "",  # no rules
    "seed=3",  # seed alone
    "nonsense",  # no target:action
    "gpu:error",  # unknown target
    "fetch:frob=1",  # unknown action
    "fetch:delay=0",  # non-positive delay
    "fetch:delay=abc",  # unparseable value
    "fetch:error@p0",  # probability out of range
    "fetch:error@0",  # every-0th
    "fetch:error@from0",  # from-0th
    "fetch",  # transport targets need an action
    "source.nan:error",  # source targets take no action
    "source.nan:rows=4",  # rows= is burst-only
    "source.garbage:delay=1",  # no transport actions on source targets
    "source.burst:rows=0",  # non-positive burst
    "source.frob",  # unknown source target
])
def test_malformed_specs_are_rejected(bad):
    with pytest.raises(ValueError):
        ChaosInjector(bad)


# -- source-chaos grammar (r7: the ingest-guard failure domain) --------------

def test_source_targets_parse_bare_with_trigger():
    inj = ChaosInjector("source.nan@3")
    fired = [inj.should("source.nan") is not None for _ in range(9)]
    assert [i + 1 for i, f in enumerate(fired) if f] == [3, 6, 9]
    reg = _metrics.get_registry()
    assert reg.counter("chaos.source.nan.injected").snapshot() == 3
    assert reg.counter("chaos.injected").snapshot() == 3


def test_burst_rows_magnitude_and_default():
    inj = ChaosInjector("source.burst:rows=8@2")
    assert inj.should("source.burst") is None
    assert inj.should("source.burst") == 8
    inj = ChaosInjector("source.burst")
    assert inj.should("source.burst") == faults.BURST_DEFAULT_EXTRA


def test_should_never_raises_or_sleeps():
    inj = ChaosInjector("source.garbage@1")
    t0 = time.perf_counter()
    for _ in range(100):
        assert inj.should("source.garbage") == faults.BURST_DEFAULT_EXTRA
    assert time.perf_counter() - t0 < 0.5
    assert inj.should("fetch") is None  # no rules for that target


def test_source_and_transport_rules_compose():
    inj = ChaosInjector("fetch:error@2,source.nan@2")
    inj.perturb("fetch")
    with pytest.raises(InjectedFault):
        inj.perturb("fetch")
    assert inj.should("source.nan") is None
    assert inj.should("source.nan") is not None


def test_poison_labels_touches_only_valid_rows():
    faults.install_chaos("source.nan@1")
    from twtml_tpu.features.featurizer import Featurizer

    statuses = list(
        SyntheticSource(total=5, seed=1, base_ms=1785320000000).produce()
    )
    batch = Featurizer(now_ms=1785320000000).featurize_batch_units(
        statuses, row_bucket=8, unit_bucket=64, pre_filtered=True
    )
    poisoned = faults.maybe_poison_labels(batch)
    valid = np.asarray(batch.mask) > 0
    assert np.isnan(poisoned.label[valid]).all()
    # padding labels stay zero: the learner multiplies by mask, and NaN
    # padding would taint every batch
    assert (poisoned.label[~valid] == 0).all()
    assert not np.isnan(np.asarray(batch.label)).any()  # input untouched


def test_corrupt_block_skips_tiny_buffers():
    faults.install_chaos("source.garbage@1")
    tiny = b'{"x": 1}\n'
    assert faults.maybe_corrupt_block(tiny) == tiny  # under the 256B floor
    big = b"x" * 1024
    out = faults.maybe_corrupt_block(big)
    assert len(out) < len(big)
    assert out != big[: len(out)]  # garbled, not just truncated


def test_bad_chaos_flag_is_a_loud_exit():
    from twtml_tpu.apps.common import install_chaos

    conf = ConfArguments().parse(["--chaos", "bogus"])
    with pytest.raises(SystemExit):
        install_chaos(conf)
    assert faults.get_chaos() is None


def test_install_uninstall_roundtrip():
    inj = faults.install_chaos("fetch:error@1000")
    assert faults.get_chaos() is inj
    faults.perturb("fetch")  # rule armed but not firing: a no-op
    assert inj.calls("fetch") == 1
    faults.uninstall_chaos()
    assert faults.get_chaos() is None
    faults.perturb("fetch")  # uninstalled: free


# -- end-to-end: the guards under chaos --------------------------------------

def _write_replay(path, total, seed):
    from tools.bench_suite import _status_json

    statuses = list(
        SyntheticSource(total=total, seed=seed, base_ms=1785320000000).produce()
    )
    with open(path, "w") as fh:
        for s in statuses:
            fh.write(json.dumps(_status_json(s)) + "\n")


CLOSED = "http://127.0.0.1:9"  # closed port: fails fast, no DNS


def test_chaos_smoke_linear_app_survives(tmp_path):
    """--chaos smoke (tier-1): the flagship app under fetch delays, an
    injected fetch error (the watchdog's re-issue path), dispatch delays,
    and a 100%-dead dashboard trains EVERY row — and the guard counters
    prove the faults actually fired and were absorbed."""
    import jax

    from twtml_tpu.apps import linear_regression as app

    jax.devices()  # lock the conftest's 8-device backend before local[1]
    path = tmp_path / "tweets.jsonl"
    _write_replay(path, 8 * 16, seed=31)

    conf = ConfArguments().parse([
        "--source", "replay", "--replayFile", str(path),
        "--seconds", "0", "--backend", "cpu",
        "--batchBucket", "16", "--tokenBucket", "64",
        "--master", "local[1]",
        "--lightning", CLOSED, "--twtweb", CLOSED, "--webTimeout", "0.2",
        "--chaos",
        "fetch:delay=0.02@3,fetch:error@7,step:delay=0.01@5,web:error,seed=1",
    ])
    totals = app.run(conf)
    assert totals["count"] == 8 * 16  # every row trained despite the chaos
    assert totals["batches"] == 8
    reg = _metrics.get_registry()
    assert reg.counter("chaos.injected").snapshot() > 0
    # the injected fetch error was absorbed by a re-issue, not an abort
    assert reg.counter("fetch.retries").snapshot() >= 1
    assert reg.counter("fetch.aborts").snapshot() == 0
    # the dead dashboard opened the breaker: failures capped at the
    # threshold, later publishes dropped without paying the timeout
    assert reg.gauge("publish.web.breaker_open").snapshot() == 1
    assert reg.counter("publish.web.failures").snapshot() >= 5
    assert reg.counter("publish.web.dropped").snapshot() >= 1


def test_chaos_wedged_fetch_aborts_with_checkpoint_then_resumes(
    tmp_path, monkeypatch
):
    """Acceptance: a fetch that stalls FOR GOOD (chaos ``from``-outage
    longer than deadline x retries) turns into a clean, checkpointed,
    non-zero-exit abort — and a restarted run RESUMES the learning curve
    from that checkpoint instead of starting over (today's alternative was
    a silent permanent hang in future.result())."""
    import jax

    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.checkpoint import Checkpointer

    jax.devices()
    path = tmp_path / "tweets.jsonl"
    _write_replay(path, 8 * 16, seed=32)
    ck = str(tmp_path / "ck")

    base = [
        "--source", "replay", "--replayFile", str(path),
        "--seconds", "0", "--backend", "cpu",
        "--batchBucket", "16", "--tokenBucket", "64",
        "--master", "local[1]",
        "--lightning", CLOSED, "--twtweb", CLOSED, "--webTimeout", "0.2",
        "--checkpointDir", ck, "--checkpointEvery", "1",
    ]
    monkeypatch.setenv("TWTML_FETCH_DEADLINE_S", "0.2")
    monkeypatch.setenv("TWTML_FETCH_RETRIES", "1")
    with pytest.raises(RuntimeError, match="runtime guard"):
        app.run(ConfArguments().parse(
            base + ["--chaos", "fetch:delay=2@from4,seed=0"]
        ))
    assert _metrics.get_registry().counter("fetch.aborts").snapshot() == 1
    # the abort flushed a checkpoint at the last delivered batch
    state, meta = Checkpointer(ck).restore()
    assert meta["batches"] == 3
    assert meta["count"] == 3 * 16

    # restart WITHOUT chaos: counters (and weights) resume from the
    # checkpoint, the intake journal replays the rows the abort stranded
    # past the cursor, and the source fast-forwards past everything
    # journaled (ISSUE 19) — every row trains EXACTLY once, so the final
    # ledger equals an unfailed run over the file (the pre-journal
    # behavior re-read the whole file on top of the restored count)
    faults.uninstall_chaos()
    totals = app.run(ConfArguments().parse(list(base)))
    assert totals["batches"] == 8
    assert totals["count"] == 8 * 16
