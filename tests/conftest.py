"""Test harness config: force an 8-device virtual CPU mesh before any JAX use.

Multi-chip TPU hardware is not available in CI; sharding/collective tests run
against an 8-device virtual CPU backend, which exercises the same
Mesh/shard_map/psum program structure the TPU path compiles. The host
environment pre-imports jax (TPU tunnel registration), so the switch happens
via jax.config — legal as long as no backend has been initialized yet.
"""

import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax without the config option (pre-backend-init here, so the
    # classic env-var route still applies — utils/backend.py keeps the same
    # fallback for the driver entry points)
    _flag = "--xla_force_host_platform_device_count=8"
    _parts = [
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    os.environ["XLA_FLAGS"] = " ".join(_parts + [_flag])

# Make the repo importable without installation (no-network image: pip install
# of the package is not possible, tests import straight from the source tree).
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

import pytest  # noqa: E402


@pytest.fixture()
def clean_properties():
    """Snapshot/restore the process property table around a test."""
    from twtml_tpu import config

    saved = dict(config._SYSTEM_PROPERTIES)
    yield config._SYSTEM_PROPERTIES
    config._SYSTEM_PROPERTIES.clear()
    config._SYSTEM_PROPERTIES.update(saved)
