"""Featurizer golden tests (reference semantics: MllibHelper.scala:42-95).

Fixture tweets in tests/data/tweets.jsonl cover: in-range retweets, out-of-range
(3 and 50000), boundary values (100, 1000 — inclusive per
MllibHelper.scala:84-87), non-retweets, emoji/accents, and timestamp_ms parsing.
"""

import json
import os

import numpy as np
import pytest

from twtml_tpu.features import Featurizer, Status
from twtml_tpu.features.hashing import hashing_tf_counts, char_bigrams

DATA = os.path.join(os.path.dirname(__file__), "data", "tweets.jsonl")


@pytest.fixture()
def statuses():
    with open(DATA, encoding="utf-8") as fh:
        return [Status.from_json(json.loads(line)) for line in fh if line.strip()]


@pytest.fixture()
def feat():
    return Featurizer(now_ms=1785320000000)  # fixed clock for determinism


def test_filtrate(statuses, feat):
    kept = [s for s in statuses if feat.filtrate(s)]
    # in range: 250, 500, 100 (boundary), 1000 (boundary), 777, 980
    assert [s.retweeted_status.retweet_count for s in kept] == [250, 500, 100, 1000, 777, 980]


def test_filtrate_rejects_non_retweets(statuses, feat):
    plain = [s for s in statuses if not s.is_retweet]
    assert len(plain) == 2
    assert all(not feat.filtrate(s) for s in plain)


def test_label_is_original_retweet_count(statuses, feat):
    s = statuses[0]
    _, _, label = feat.featurize(s)
    assert label == 250.0


def test_text_features_hash_original_lowercased(statuses, feat):
    s = statuses[0]  # original text: "Breaking news from the summit today!"
    counts = feat.featurize_text(s)
    expected = hashing_tf_counts(
        char_bigrams("breaking news from the summit today!"), 1000
    )
    assert counts == expected
    # Never hashes the RT-wrapper text.
    wrapper = hashing_tf_counts(
        char_bigrams("rt @alice: breaking news from the summit today!"), 1000
    )
    assert counts != wrapper


def test_numeric_feature_scaling(statuses, feat):
    s = statuses[0]
    nums = feat.featurize_numbers(s)
    orig = s.retweeted_status
    assert nums[0] == pytest.approx(50000 * 1e-12)
    assert nums[1] == pytest.approx(1200 * 1e-12)
    assert nums[2] == pytest.approx(900 * 1e-12)
    age_ms = 1785320000000 - orig.created_at_ms
    assert age_ms > 0
    assert nums[3] == pytest.approx(age_ms * 1e-14, rel=1e-6)


def test_timestamp_ms_parsing(statuses):
    s = statuses[7]
    assert s.retweeted_status.created_at_ms == 1785315612000


def test_created_at_parsing(statuses):
    # "Mon Jul 27 09:00:00 +0000 2026"
    assert statuses[0].retweeted_status.created_at_ms == 1785142800000


def test_num_text_features_takes_effect():
    """The reference's reset() shadows its own fields (MllibHelper.scala:27-29)
    so --numTextFeatures never reaches the hasher; ours must apply it."""
    big = Featurizer(num_text_features=2**18, now_ms=0)
    s = Status(retweeted_status=Status(text="Deep learning on TPUs", retweet_count=500))
    counts = big.featurize_text(s)
    assert all(0 <= idx < 2**18 for idx in counts)
    assert big.num_features == 2**18 + 4


def test_featurize_batch_padding(statuses, feat):
    batch = feat.featurize_batch(statuses)
    assert batch.num_valid == 6
    # padded to power-of-two bucket
    assert batch.token_idx.shape[0] == 8
    assert batch.token_idx.shape == batch.token_val.shape
    assert batch.numeric.shape == (8, 4)
    assert batch.mask.tolist() == [1, 1, 1, 1, 1, 1, 0, 0]
    # padded token slots are zero-valued so scatter-adds are no-ops
    assert batch.token_val[batch.mask == 0].sum() == 0


def test_accent_normalization_optional():
    s = Status(retweeted_status=Status(text="café", retweet_count=500))
    raw = Featurizer(now_ms=0).featurize_text(s)
    norm = Featurizer(now_ms=0, normalize_accents=True).featurize_text(s)
    expected_norm = hashing_tf_counts(char_bigrams("cafe"), 1000)
    assert norm == expected_norm
    assert raw == hashing_tf_counts(char_bigrams("café"), 1000)
    assert raw != norm

def test_compact_wire_dtypes(statuses, feat):
    """Default 1004-dim schema travels int16 indices + uint16 counts; the
    wire dtype is a schema decision (stable across batches), not data-sniffed
    (host→device transfer is the streaming hot loop's bottleneck)."""
    batch = feat.featurize_batch(statuses)
    assert batch.token_idx.dtype == np.int16
    assert batch.token_val.dtype == np.uint16
    # an empty batch keeps the exact same dtypes — one compiled program
    empty = feat.featurize_batch([])
    assert empty.token_idx.dtype == np.int16
    assert empty.token_val.dtype == np.uint16


def test_compact_wire_dtypes_large_feature_space(statuses):
    """2^18-dim hashing keeps int32 indices (int16 can't address them)."""
    feat = Featurizer(num_text_features=2**18, now_ms=0)
    batch = feat.featurize_batch(statuses)
    assert batch.token_idx.dtype == np.int32
    assert batch.token_val.dtype == np.uint16


def test_compact_wire_dtypes_lossless(statuses, feat):
    """Compact batch decodes to the identical sparse features as the
    python ground-truth path."""
    batch = feat.featurize_batch(statuses)
    kept = [s for s in statuses if feat.filtrate(s)]
    for i, s in enumerate(kept):
        expected = feat.featurize_text(s)
        got = {
            int(ix): float(v)
            for ix, v in zip(batch.token_idx[i], batch.token_val[i])
            if v
        }
        assert got == expected


def test_pad_feature_batch_non_count_values_stay_float():
    """A generic caller with real-valued token_val (counts=False default)
    keeps float32 on the wire — never downcast by data coincidence."""
    from twtml_tpu.features.batch import pad_feature_batch

    rows = [({1: 2.0, 3: 1.0}, np.zeros(4, np.float32), 5.0)]  # integral...
    batch = pad_feature_batch(rows, num_features=1004)
    assert batch.token_val.dtype == np.float32  # ...but schema says no counts
    assert batch.token_idx.dtype == np.int16  # indices still compact

def test_compact_tokens_misdeclared_schema_raises():
    """Out-of-range indices or counts fail loudly instead of silently
    wrapping (int16) or switching wire dtype mid-stream (float32)."""
    from twtml_tpu.features.batch import compact_tokens

    idx = np.array([[1, 40000]], dtype=np.int32)
    val = np.array([[1.0, 1.0]], dtype=np.float32)
    with pytest.raises(ValueError):
        compact_tokens(idx, val, 1000, counts=True)
    big = np.array([[70000.0]], dtype=np.float32)
    with pytest.raises(ValueError):
        compact_tokens(np.array([[1]], np.int32), big, 1000, counts=True)

def test_compact_tokens_rejects_fractional_and_negative():
    """counts=True values must survive the uint16 round-trip exactly:
    TF-IDF-style fractional weights, negatives, and negative indices all
    raise instead of silently truncating/wrapping."""
    from twtml_tpu.features.batch import compact_tokens

    ok_idx = np.array([[1, 2]], dtype=np.int32)
    for bad in ([[0.7, 1.0]], [[-1.0, 1.0]]):
        with pytest.raises(ValueError):
            compact_tokens(
                ok_idx, np.array(bad, dtype=np.float32), 1000, counts=True
            )
    with pytest.raises(ValueError):
        compact_tokens(
            np.array([[-5, 2]], dtype=np.int32),
            np.array([[1.0, 1.0]], dtype=np.float32),
            1000,
            counts=True,
        )
