"""Sanitized native differential (slow): tools/native_sanity.py under
ASan+UBSan. The C parity fast paths get the same dynamic scrutiny as the
Python side — memory errors abort the harness, semantic divergence exits 1.
Skips where the toolchain can't produce an instrumented build."""

import os
import shutil
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DRIVER = os.path.join(_REPO, "tools", "native_sanity.py")


def _runtime(name: str) -> str | None:
    if shutil.which("g++") is None:
        return None
    out = subprocess.run(
        ["g++", f"-print-file-name={name}"],
        capture_output=True, text=True, timeout=30,
    ).stdout.strip()
    return out if os.path.sep in out and os.path.exists(out) else None


@pytest.mark.parametrize("modes", ["ubsan", "asan,ubsan"])
def test_native_differentials_under_sanitizers(modes):
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    for mode, rt in (("asan", "libasan.so"), ("ubsan", "libubsan.so")):
        if mode in modes and _runtime(rt) is None:
            pytest.skip(f"{rt} unavailable")
    env = dict(os.environ)
    env["TWTML_NATIVE_SANITIZE"] = modes
    env.pop("TWTML_NATIVE_LIB", None)  # harness picks its own temp path
    proc = subprocess.run(
        [sys.executable, _DRIVER], env=env, cwd=_REPO,
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (
        f"native_sanity({modes}) rc={proc.returncode}\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    assert "PASS" in proc.stdout
