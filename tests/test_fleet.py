"""Read fleet (ISSUE 11): router policies, ejection + jittered re-probe,
client retry, the multi-process tear invariant ACROSS replicas, SIGKILL →
zero client-visible errors, and the zero-train-fetch acceptance with a
fleet + shadow challengers live.

The fleet laws under test:

- **tear invariant across replicas**: while a trainer publishes new
  promotable snapshots mid-load, EVERY response routed through the fleet
  bit-matches the snapshot step it claims — replicas promote independently
  but each response names (and matches) exactly one stamped step;
- **failure is drained, not surfaced**: a SIGKILLed replica is ejected
  behind a jittered backoff and its traffic retried on the others — zero
  client-visible errors;
- **the read fleet is a side-channel**: with a router, a replica plane, a
  promoter, and shadow challengers all live against the trainer's
  checkpoint directory, the train path still fetches exactly once per
  batch and produces bit-identical weights to a no-fleet control.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from twtml_tpu.config import ConfArguments  # noqa: E402
from twtml_tpu.features.featurizer import Featurizer  # noqa: E402
from twtml_tpu.models import (  # noqa: E402
    StreamingLinearRegressionWithSGD,
)
from twtml_tpu.serving.client import ServingClient, ServingError  # noqa: E402
from twtml_tpu.serving.fleet import FleetRouter  # noqa: E402
from twtml_tpu.serving.plane import ServingPlane  # noqa: E402
from twtml_tpu.serving.snapshot import (  # noqa: E402
    ServingSnapshot,
    SnapshotPromoter,
    load_servable,
)
from twtml_tpu.streaming.sources import SyntheticSource  # noqa: E402
from twtml_tpu.telemetry import metrics as _metrics  # noqa: E402
from twtml_tpu.web.cache import ApiCache  # noqa: E402
from twtml_tpu.web.server import Server  # noqa: E402

NOW_MS = 1785320000000
CLOSED = "http://127.0.0.1:9"  # closed port: telemetry best-effort no-ops


@pytest.fixture(autouse=True)
def _clean():
    _metrics.reset_for_tests()
    yield
    _metrics.reset_for_tests()


def _statuses(n, seed=3):
    return list(SyntheticSource(total=n, seed=seed).produce())


def _feat():
    return Featurizer(now_ms=NOW_MS)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _save_ckpt(directory, step, weights, level="ok"):
    from twtml_tpu.checkpoint import Checkpointer

    meta = {"count": step * 10, "batches": step,
            "quality": {"level": level, "drift_score": 0.5,
                        "loss_trend": 0.0}}
    return Checkpointer(str(directory)).save(
        step, np.asarray(weights, np.float32), meta
    )


def _weights_for_step(step):
    """Deterministic per-step weights, recomputable in any process."""
    rng = np.random.default_rng(100 + step)
    return (rng.standard_normal(1004) * 1e-2).astype(np.float32)


def _refs_for_steps(steps, statuses, row_bucket=32):
    import jax

    batch = _feat().featurize_batch_ragged(
        statuses, row_bucket=row_bucket, pre_filtered=True
    )
    mask = np.asarray(batch.mask) > 0
    refs = {}
    for step in steps:
        model = StreamingLinearRegressionWithSGD().set_initial_weights(
            _weights_for_step(step)
        )
        refs[step] = np.asarray(
            jax.device_get(model.step(batch)).predictions
        )[mask]
    return refs


def _replica(tmp_path, name, snapshot, **plane_kw):
    """One in-process replica: plane + real HTTP server; returns
    (url, plane, server)."""
    plane_kw.setdefault("featurizer", _feat())
    plane_kw.setdefault("batch_rows", 32)
    plane_kw.setdefault("max_wait_ms", 2.0)
    plane_kw.setdefault("depth", 4)
    plane = ServingPlane(snapshot, **plane_kw).start()
    server = Server(
        port=0, host="127.0.0.1",
        cache=ApiCache(backup_file=str(tmp_path / f"{name}.json")),
    ).attach_serving(plane)
    server.start_background()
    url = f"http://127.0.0.1:{server._runner.addresses[0][1]}"
    return url, plane, server


def _rows_for(statuses):
    return [{
        "text": s.retweeted_status.text,
        "followers_count": s.retweeted_status.followers_count,
        "favourites_count": s.retweeted_status.favourites_count,
        "friends_count": s.retweeted_status.friends_count,
        "created_at_ms": s.retweeted_status.created_at_ms,
        "retweet_count": s.retweeted_status.retweet_count,
    } for s in statuses]


# ---------------------------------------------------------------------------
# router core: policies, ejection, retries (in-process replicas, real HTTP)

def test_router_smoke_single_replica(tmp_path):
    """The CI fleet smoke: a real router process loop (apps.router.run)
    over one replica — one predict roundtrip through the front door, a
    live /api/fleet view, clean shutdown."""
    from twtml_tpu.apps import router as router_app

    snap = ServingSnapshot(step=1, weights=_weights_for_step(1),
                           meta={"quality": {"level": "ok"}})
    url, plane, server = _replica(tmp_path, "r0", snap)
    stop = threading.Event()
    ready = {}
    ready_evt = threading.Event()

    def started(srv, rt):
        ready["port"] = srv._runner.addresses[0][1]
        ready_evt.set()

    conf = ConfArguments().parse([
        "--replicas", url, "--routerPort", "0", "--routePolicy", "p99",
    ])
    result = {}

    def runner():
        result["stats"] = router_app.run(conf, started=started,
                                         stop_event=stop)

    thread = threading.Thread(target=runner)
    thread.start()
    try:
        assert ready_evt.wait(timeout=60), "router never came up"
        client = ServingClient(f"http://127.0.0.1:{ready['port']}")
        statuses = _statuses(6, seed=2)
        res = client.predict(_rows_for(statuses))
        assert res["snapshotStep"] == 1 and res["servedRows"] == 6
        view = client.fleet()
        assert view["jsonClass"] == "Fleet" and view["policy"] == "p99"
        assert len(view["replicas"]) == 1
        assert view["replicas"][0]["healthy"]
        assert view["requests"] >= 1 and view["ejections"] == 0
    finally:
        stop.set()
        thread.join(timeout=60)
        server.stop()
        plane.stop()
    assert not thread.is_alive()
    assert result["stats"]["requests"] >= 1


def test_route_policy_p99_spreads_and_hash_sticks(tmp_path):
    snap = ServingSnapshot(step=1, weights=_weights_for_step(1))
    url_a, plane_a, srv_a = _replica(tmp_path, "a", snap)
    url_b, plane_b, srv_b = _replica(
        tmp_path, "b", ServingSnapshot(step=1, weights=_weights_for_step(1))
    )
    body = json.dumps(
        {"rows": [{"text": "route me", "created_at_ms": NOW_MS}]}
    ).encode()
    try:
        p99 = FleetRouter([url_a, url_b], policy="p99")
        for _ in range(8):
            status, _payload = p99.predict(body)
            assert status == 200
        counts = [r.requests for r in p99.replicas]
        assert all(c > 0 for c in counts)  # ties round-robin: both serve

        sticky = FleetRouter([url_a, url_b], policy="hash")
        for _ in range(6):
            status, _payload = sticky.predict(body)
            assert status == 200
        counts = [r.requests for r in sticky.replicas]
        # one key -> ONE replica, every time
        assert sorted(counts) == [0, 6]
        # many distinct keys spread over the ring
        for i in range(32):
            key_body = json.dumps({"rows": [f"key {i}"]}).encode()
            status, _payload = sticky.predict(key_body)
            assert status == 200
        assert all(r.requests > 0 for r in sticky.replicas)
    finally:
        for srv, plane in ((srv_a, plane_a), (srv_b, plane_b)):
            srv.stop()
            plane.stop()


def test_dead_replica_ejects_retries_and_recovers(tmp_path):
    """A dead replica's forward retries on the live one (counted), ejects
    the dead one behind a backoff (counted), and a later health probe
    restores it once it answers again."""
    snap = ServingSnapshot(step=1, weights=_weights_for_step(1))
    url_live, plane, srv = _replica(tmp_path, "live", snap)
    dead_port = _free_port()
    url_dead = f"http://127.0.0.1:{dead_port}"
    body = json.dumps({"rows": ["hello fleet"]}).encode()
    try:
        router = FleetRouter([url_dead, url_live], policy="p99")
        ok = 0
        for _ in range(6):
            status, payload = router.predict(body)
            assert status == 200, payload
            ok += 1
        assert ok == 6  # the dead replica never surfaced an error
        reg = _metrics.get_registry()
        assert reg.counter("router.retries").snapshot() >= 1
        assert reg.counter("fleet.replica_ejections").snapshot() >= 1
        view = router.stats()
        by_url = {r["url"]: r for r in view["replicas"]}
        assert not by_url[url_dead]["healthy"]
        assert by_url[url_live]["healthy"]
        assert view["ejections"] >= 1 and view["retries"] >= 1

        # a replica coming up at the dead address is restored by the probe
        srv2 = Server(
            port=dead_port, host="127.0.0.1",
            cache=ApiCache(backup_file=str(tmp_path / "late.json")),
        ).attach_serving(plane)
        srv2.start_background()
        try:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                router.replicas[0].ejected_until = 0.0  # skip the backoff
                router.health_check_once()
                if router.replicas[0].healthy:
                    break
                time.sleep(0.05)
            assert router.replicas[0].healthy
            assert reg.counter("fleet.replica_restores").snapshot() >= 1
        finally:
            srv2.stop()
    finally:
        srv.stop()
        plane.stop()


def test_all_replicas_down_is_clean_503():
    router = FleetRouter(
        [f"http://127.0.0.1:{_free_port()}",
         f"http://127.0.0.1:{_free_port()}"],
    )
    status, payload = router.predict(b'{"rows": ["x"]}')
    assert status == 503
    assert "replica" in json.loads(payload.decode())["error"]
    assert _metrics.get_registry().counter("router.errors").snapshot() == 1


def test_bad_request_passes_through_without_ejection(tmp_path):
    """A 4xx is the request's fault: no retry, no ejection — every replica
    would agree."""
    snap = ServingSnapshot(step=1, weights=_weights_for_step(1))
    url, plane, srv = _replica(tmp_path, "r", snap)
    try:
        router = FleetRouter([url])
        status, payload = router.predict(b'{"rows": 7}')
        assert status == 400
        assert "bad predict request" in json.loads(payload.decode())["error"]
        assert router.replicas[0].healthy
        reg = _metrics.get_registry()
        assert reg.counter("router.retries").snapshot() == 0
        assert reg.counter("fleet.replica_ejections").snapshot() == 0
    finally:
        srv.stop()
        plane.stop()


def test_client_jittered_retry_on_503_and_connection_refused():
    """ServingClient retries 503/connection-refused on the Source._backoff
    cap+jitter ladder (counted in serve.client_retries); a non-retryable
    failure raises immediately."""
    client = ServingClient(f"http://127.0.0.1:{_free_port()}",
                           timeout=1.0, retries=2)
    t0 = time.monotonic()
    with pytest.raises(ServingError):
        client.predict(["x"])
    # two jittered sleeps happened: >= 0.5x of (0.1 + 0.2)
    assert time.monotonic() - t0 >= 0.15
    assert _metrics.get_registry().counter(
        "serve.client_retries").snapshot() == 2

    # the ladder: jittered into [0.5x, 1x], capped
    for attempt, base in ((1, 0.1), (2, 0.2), (3, 0.4), (30, 2.0)):
        for _ in range(8):
            b = ServingClient._backoff(attempt)
            assert 0.5 * base <= b <= base

    # retries=0 keeps the legacy fail-fast face
    fast = ServingClient(f"http://127.0.0.1:{_free_port()}",
                         timeout=1.0, retries=0)
    with pytest.raises(ServingError):
        fast.predict(["x"])
    assert _metrics.get_registry().counter(
        "serve.client_retries").snapshot() == 2  # unchanged


# ---------------------------------------------------------------------------
# multi-process fleet: the tear invariant + SIGKILL ejection

def _spawn_replica(ck, port, tmp_path, name):
    env = dict(os.environ)
    env["TWTML_NOW_MS"] = str(NOW_MS)
    env.pop("XLA_FLAGS", None)  # 1-device replica; the worker pins cpu
    out = open(tmp_path / f"{name}.log", "w")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "serve_worker.py"),
         "--checkpointDir", str(ck), "--servePort", str(port),
         "--serveBatchRows", "32", "--serveMaxWaitMs", "2",
         "--servePromoteEvery", "0.1", "--backend", "cpu",
         "--master", "local[1]"],
        env=env, stdout=out, stderr=subprocess.STDOUT,
    )
    return proc, out


def _wait_step(url, step, deadline_s=300.0):
    client = ServingClient(url, timeout=2.0, retries=0)
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            if client.serving().get("snapshotStep", -1) >= step:
                return True
        except Exception:
            pass
        time.sleep(0.25)
    return False


def test_fleet_two_replica_processes_tear_invariant_and_sigkill(tmp_path):
    """ACCEPTANCE (ISSUE 11): real router loop + 2 REAL replica processes
    over HTTP. While the 'trainer' (this test) publishes new promotable
    snapshots mid-load, every routed response bit-matches its claimed
    snapshot step; then a SIGKILLed replica is ejected with ZERO
    client-visible errors."""
    from twtml_tpu.apps import router as router_app

    ck = tmp_path / "ck"
    _save_ckpt(ck, 1, _weights_for_step(1))
    statuses = _statuses(8, seed=21)
    refs = _refs_for_steps((1, 2, 3), statuses)
    rows = _rows_for(statuses)

    ports = (_free_port(), _free_port())
    procs = []
    logs = []
    stop = threading.Event()
    router_thread = None
    try:
        for i, port in enumerate(ports):
            proc, out = _spawn_replica(ck, port, tmp_path, f"replica{i}")
            procs.append(proc)
            logs.append(out)
        urls = [f"http://127.0.0.1:{p}" for p in ports]
        for url in urls:
            assert _wait_step(url, 1), (
                f"replica {url} never promoted step 1; see tmp logs"
            )

        ready = {}
        ready_evt = threading.Event()

        def started(srv, rt):
            ready["router"] = rt
            ready["port"] = srv._runner.addresses[0][1]
            ready_evt.set()

        conf = ConfArguments().parse([
            "--replicas", ",".join(urls), "--routerPort", "0",
        ])
        router_thread = threading.Thread(
            target=router_app.run,
            kwargs=dict(conf=conf, started=started, stop_event=stop),
        )
        router_thread.start()
        assert ready_evt.wait(timeout=60), "router never came up"
        client = ServingClient(f"http://127.0.0.1:{ready['port']}",
                               timeout=60.0, retries=2)

        responses = []

        def load(n):
            for _ in range(n):
                responses.append(client.predict(rows))

        # phase 1: both replicas on step 1
        load(6)
        # trainer publishes step 2 mid-load; replicas promote independently
        _save_ckpt(ck, 2, _weights_for_step(2))
        load(4)
        for url in urls:
            assert _wait_step(url, 2)
        load(4)
        # ...and step 3
        _save_ckpt(ck, 3, _weights_for_step(3))
        for url in urls:
            assert _wait_step(url, 3)
        load(6)

        # THE tear invariant ACROSS replicas: every response bit-matches
        # the snapshot step it claims, whichever replica served it and
        # wherever in the promotion race it landed
        seen_steps = set()
        for res in responses:
            step = res["snapshotStep"]
            seen_steps.add(step)
            assert step in refs, f"response claims unknown step {step}"
            assert np.array_equal(
                refs[step], np.asarray(res["predictions"], np.float32)
            ), f"response torn vs its claimed snapshot (step {step})"
        assert 1 in seen_steps and 3 in seen_steps

        # SIGKILL one replica mid-fleet: traffic must keep flowing with
        # ZERO client-visible errors (router retries + ejects; the client
        # ladder covers any residual window)
        procs[0].send_signal(signal.SIGKILL)
        procs[0].wait(timeout=30)
        survivors = []
        for _ in range(12):
            res = client.predict(rows)  # raises on any client-visible error
            survivors.append(res)
        for res in survivors:
            assert np.array_equal(
                refs[3], np.asarray(res["predictions"], np.float32)
            )
        # Ejection is ASYNCHRONOUS to the predicts above: the router only
        # marks the dead replica on a failed forward OR its periodic
        # health probe, and under full-suite contention the p99 policy may
        # legitimately route all 12 predicts to the healthy replica before
        # either has happened (the PR 12 flake). Bound the wait on the
        # documented ejection contract — the health-check cadence plus the
        # jittered backoff ladder's first rungs — instead of asserting a
        # racing snapshot (or sleeping a fixed guess).
        deadline = time.monotonic() + 30.0
        view = client.fleet()
        while time.monotonic() < deadline:
            by_url = {r["url"]: r for r in view["replicas"]}
            if not by_url[urls[0]]["healthy"] and view["ejections"] >= 1:
                break
            time.sleep(0.25)
            view = client.fleet()
        by_url = {r["url"]: r for r in view["replicas"]}
        assert not by_url[urls[0]]["healthy"], (
            "dead replica never ejected within the health-check window"
        )
        assert by_url[urls[1]]["healthy"]
        assert view["ejections"] >= 1
    finally:
        stop.set()
        if router_thread is not None:
            router_thread.join(timeout=60)
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
        for out in logs:
            out.close()


# ---------------------------------------------------------------------------
# acceptance: the fleet is a read-only side-channel of the train path

def _write_replay(tmp_path, n, seed=31):
    path = tmp_path / "tweets.jsonl"
    with open(path, "w", encoding="utf-8") as fh:
        for s in SyntheticSource(total=n, seed=seed, base_ms=NOW_MS).produce():
            d = {
                "text": s.text, "retweet_count": s.retweet_count,
                "user": {"followers_count": s.followers_count,
                         "favourites_count": s.favourites_count,
                         "friends_count": s.friends_count},
                "timestamp_ms": str(s.created_at_ms), "lang": s.lang or "en",
            }
            if s.retweeted_status is not None:
                r = s.retweeted_status
                d["retweeted_status"] = {
                    "text": r.text, "retweet_count": r.retweet_count,
                    "user": {"followers_count": r.followers_count,
                             "favourites_count": r.favourites_count,
                             "friends_count": r.friends_count},
                    "timestamp_ms": str(r.created_at_ms),
                }
            fh.write(json.dumps(d) + "\n")
    return path


def test_fleet_and_shadow_challengers_add_zero_train_fetches(
    tmp_path, monkeypatch
):
    """ACCEPTANCE: with a FULL fleet live against the trainer's checkpoint
    directory — an --abtest (champion + shadow challengers) replica plane,
    its promoter, a replica HTTP server, and a fleet router — the
    --tenants 2 train path still fetches exactly once per batch, and the
    trained champion/challenger stack is bit-identical to a no-fleet
    control."""
    import jax

    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.checkpoint import Checkpointer
    from twtml_tpu.serving.abtest import ChampionEngine

    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    path = _write_replay(tmp_path, 8 * 16)
    base = [
        "--source", "replay", "--replayFile", str(path),
        "--seconds", "0", "--backend", "cpu", "--master", "local[1]",
        "--batchBucket", "16", "--tokenBucket", "64", "--tenants", "2",
        "--lightning", CLOSED, "--twtweb", CLOSED, "--webTimeout", "0.2",
    ]

    # control run: no fleet anywhere
    ck_a = str(tmp_path / "ck_a")
    app.run(ConfArguments().parse(
        base + ["--checkpointDir", ck_a, "--checkpointEvery", "2"]
    ))
    control_state, control_meta = Checkpointer(ck_a).restore()

    # fleet-live run: abtest replica + promoter + router, all against ck_b
    ck_b = tmp_path / "ck_b"
    stack0 = np.zeros((2, 1004), np.float32)
    from twtml_tpu.checkpoint import Checkpointer as _Ck

    _Ck(str(ck_b)).save(0, stack0, {
        "count": 0, "batches": 0,
        "quality": {"level": "ok", "tenants": [
            {"tenant": 0, "level": "ok", "loss": 5.0},
            {"tenant": 1, "level": "ok", "loss": 9.0},
        ]},
    })
    snap, _reason = load_servable(str(ck_b))
    engine = ChampionEngine(num_text_features=1000, num_tenants=2)
    url, plane, server = _replica(
        tmp_path, "accept", snap, engine=engine
    )
    promoter = SnapshotPromoter(str(ck_b), plane, poll_s=0.05).start()
    router = FleetRouter([url]).start()
    router_server = Server(
        port=0, host="127.0.0.1",
        cache=ApiCache(backup_file=str(tmp_path / "router.json")),
    ).attach_fleet(router)
    router_server.start_background()
    router_url = f"http://127.0.0.1:{router_server._runner.addresses[0][1]}"

    # prove the fleet serves BEFORE the counting window (a predict is a
    # legitimate serve-path fetch; the law counts TRAIN-path fetches)
    res = ServingClient(router_url).predict(["warm the fleet"])
    assert res["servedRows"] == 1 and res["snapshotStep"] == 0

    calls = {"n": 0}
    real_get = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real_get(x)

    jax.device_get = counting
    try:
        totals = app.run(ConfArguments().parse(
            base + ["--checkpointDir", str(ck_b), "--checkpointEvery", "2"]
        ))
    finally:
        jax.device_get = real_get
    assert totals["batches"] == 8
    # ONE stacked fetch per train tick — the whole fleet added none
    assert calls["n"] == 8

    # the fleet converged on the trainer's newest stamped step
    deadline = time.monotonic() + 10
    while plane.snapshot_step != totals["batches"] and (
        time.monotonic() < deadline
    ):
        promoter.poll_once()
        time.sleep(0.01)
    assert plane.snapshot_step == totals["batches"]

    promoter.stop()
    router.stop()
    router_server.stop()
    server.stop()
    plane.stop()

    # bit-identity: the champion/challenger stack trained identically
    fleet_state, fleet_meta = Checkpointer(str(ck_b)).restore()
    assert fleet_meta["count"] == control_meta["count"]
    assert np.array_equal(np.asarray(control_state),
                          np.asarray(fleet_state))
