"""Config/CLI tests mirroring the reference's ConfArgumentsSuite
(spark/src/test/scala/com/giorgioinf/twtml/spark/ConfArgumentsSuite.scala:41-142):
defaults from reference.conf, OAuth routing into the property table, and
long-flag + short-flag round-trips of every knob.
"""

import pytest

from twtml_tpu import config as cfg
from twtml_tpu.config import ConfArguments

LIGHTNING_DEF = "http://public.lightning-viz.org"
TWTWEB_DEF = "http://localhost:8888"

MASTER = "local[4]"
NAME = "twtml-tpu-test"
LIGHTNING = "http://lightninghost"
TWTWEB = "http://twtwebhost"
SECONDS = 123
STEP_SIZE = 0.01234
NUM_ITERATIONS = 123
MINI_BATCH_FRACTION = 1.23
NUM_RETWEET_BEGIN = 1234
NUM_RETWEET_END = 12345678
NUM_TEXT_FEATURES = 123456
CONSUMER_KEY = "1234567"
CONSUMER_SECRET = "12345678"
ACCESS_TOKEN = "123456789"
ACCESS_TOKEN_SECRET = "1234567890"


def twt(key):
    return cfg.get_property("twitter4j.oauth." + key)


@pytest.fixture()
def isolated_env(tmp_path, monkeypatch):
    """Defaults tests must not pick up a developer's application.conf/cwd."""
    monkeypatch.delenv("TWTML_CONFIG", raising=False)
    monkeypatch.chdir(tmp_path)


def test_config_initialization_reference_conf(isolated_env):
    conf = ConfArguments().setAppName(NAME)
    assert conf.appName() == NAME
    assert conf.lightning == LIGHTNING_DEF
    assert conf.twtweb == TWTWEB_DEF


def test_config_reference_conf_defaults(isolated_env):
    conf = ConfArguments()
    assert conf.seconds == 5
    assert conf.stepSize == 0.005
    assert conf.numIterations == 50
    assert conf.miniBatchFraction == 1.0
    assert conf.numRetweetBegin == 100
    assert conf.numRetweetEnd == 1000
    assert conf.numTextFeatures == 1000


def test_config_long_arguments(clean_properties):
    conf = ConfArguments().parse([
        "--master", MASTER,
        "--name", NAME,
        "--consumerKey", CONSUMER_KEY,
        "--consumerSecret", CONSUMER_SECRET,
        "--accessToken", ACCESS_TOKEN,
        "--accessTokenSecret", ACCESS_TOKEN_SECRET,
        "--lightning", LIGHTNING,
        "--twtweb", TWTWEB,
        "--seconds", str(SECONDS),
        "--stepSize", str(STEP_SIZE),
        "--numIterations", str(NUM_ITERATIONS),
        "--miniBatchFraction", str(MINI_BATCH_FRACTION),
        "--numRetweetBegin", str(NUM_RETWEET_BEGIN),
        "--numRetweetEnd", str(NUM_RETWEET_END),
        "--numTextFeatures", str(NUM_TEXT_FEATURES),
    ])
    _assert_parsed(conf)


def test_config_short_arguments(clean_properties):
    conf = ConfArguments().parse([
        "-m", MASTER,
        "-n", NAME,
        "-C", CONSUMER_KEY,
        "-S", CONSUMER_SECRET,
        "-A", ACCESS_TOKEN,
        "-T", ACCESS_TOKEN_SECRET,
        "-l", LIGHTNING,
        "-w", TWTWEB,
        "-s", str(SECONDS),
        "-p", str(STEP_SIZE),
        "-i", str(NUM_ITERATIONS),
        "-b", str(MINI_BATCH_FRACTION),
        "-B", str(NUM_RETWEET_BEGIN),
        "-E", str(NUM_RETWEET_END),
        "-f", str(NUM_TEXT_FEATURES),
    ])
    _assert_parsed(conf)


def _assert_parsed(conf):
    assert conf.master == MASTER
    assert conf.appName() == NAME
    assert twt("consumerKey") == CONSUMER_KEY
    assert twt("consumerSecret") == CONSUMER_SECRET
    assert twt("accessToken") == ACCESS_TOKEN
    assert twt("accessTokenSecret") == ACCESS_TOKEN_SECRET
    assert conf.lightning == LIGHTNING
    assert conf.twtweb == TWTWEB
    assert conf.seconds == SECONDS
    assert conf.stepSize == STEP_SIZE
    assert conf.numIterations == NUM_ITERATIONS
    assert conf.miniBatchFraction == MINI_BATCH_FRACTION
    assert conf.numRetweetBegin == NUM_RETWEET_BEGIN
    assert conf.numRetweetEnd == NUM_RETWEET_END
    assert conf.numTextFeatures == NUM_TEXT_FEATURES


def test_help_exits_zero():
    with pytest.raises(SystemExit) as exc:
        ConfArguments().parse(["--help"])
    assert exc.value.code == 0


def test_unknown_flag_exits_nonzero():
    with pytest.raises(SystemExit) as exc:
        ConfArguments().parse(["--definitely-not-a-flag"])
    assert exc.value.code == 1


def test_extension_flags():
    conf = ConfArguments().parse([
        "--backend", "tpu",
        "--source", "synthetic",
        "--replayFile", "/tmp/tweets.jsonl",
        "--l2Reg", "0.1",
        "--dtype", "bfloat16",
    ])
    assert conf.backend == "tpu"
    assert conf.source == "synthetic"
    assert conf.replayFile == "/tmp/tweets.jsonl"
    assert conf.l2Reg == 0.1
    assert conf.dtype == "bfloat16"


def test_local_shards_hint():
    assert ConfArguments().parse(["-m", "local[4]"]).local_shards() == 4
    assert ConfArguments().parse(["-m", "local[*]"]).local_shards() is None
    assert ConfArguments().local_shards() is None


def test_application_conf_layering(tmp_path, monkeypatch, clean_properties):
    app_conf = tmp_path / "application.conf"
    app_conf.write_text('seconds="9"\nconsumerKey="abc"\n')
    monkeypatch.setenv("TWTML_CONFIG", str(app_conf))
    conf = ConfArguments()
    assert conf.seconds == 9
    assert twt("consumerKey") == "abc"
    # untouched keys keep reference defaults
    assert conf.stepSize == 0.005


def test_hash_on_flag_and_validation(isolated_env, tmp_path, monkeypatch):
    assert ConfArguments().hashOn == "device"
    assert ConfArguments().parse(["--hashOn", "host"]).hashOn == "host"
    with pytest.raises(SystemExit):
        ConfArguments().parse(["--hashOn", "gpu"])
    # config-file typos fail loudly too, not silently fall back (the CLI and
    # file paths validate identically)
    bad = tmp_path / "application.conf"
    bad.write_text('hashOn="Device"\n')
    monkeypatch.setenv("TWTML_CONFIG", str(bad))
    with pytest.raises(ValueError):
        ConfArguments()


def test_token_bucket_flag(isolated_env):
    assert ConfArguments().tokenBucket == 0
    assert ConfArguments().parse(["--tokenBucket", "128"]).tokenBucket == 128


def test_multihost_flags_and_twtml_master(isolated_env):
    conf = ConfArguments().parse([
        "--coordinator", "10.0.0.1:1234",
        "--numProcesses", "4", "--processId", "2",
    ])
    conf.validate_master()
    assert conf.multihost() == ("10.0.0.1:1234", 4, 2)

    # twtml:// master URL is the one-flag cluster form: fills --coordinator
    conf = ConfArguments().parse([
        "--master", "twtml://10.0.0.9:7077",
        "--numProcesses", "2", "--processId", "0",
    ])
    conf.validate_master()
    assert conf.coordinator == "10.0.0.9:7077"
    assert conf.multihost() == ("10.0.0.9:7077", 2, 0)

    # single-host stays single-host
    conf = ConfArguments()
    conf.validate_master()
    assert conf.multihost() is None


def test_unsupported_master_scheme_rejected(isolated_env):
    # the reference accepts spark://host:port (ConfArguments.scala:95-98);
    # this runtime can't honor it, and silently running single-host would
    # be worse than rejecting (VERDICT r2) — so it rejects, loudly
    conf = ConfArguments().parse(["--master", "spark://h:7077"])
    with pytest.raises(SystemExit):
        conf.validate_master()
    conf = ConfArguments().parse(["--master", "twtml://"])
    with pytest.raises(SystemExit):
        conf.validate_master()
    # conflicting coordinator vs master URL
    conf = ConfArguments().parse([
        "--master", "twtml://a:1", "--coordinator", "b:2",
    ])
    with pytest.raises(SystemExit):
        conf.validate_master()


def test_multihost_coordinate_validation(isolated_env):
    conf = ConfArguments().parse(["--coordinator", "h:1"])
    with pytest.raises(SystemExit):
        conf.multihost()  # missing --numProcesses/--processId
    conf = ConfArguments().parse([
        "--coordinator", "h:1", "--numProcesses", "2", "--processId", "5",
    ])
    with pytest.raises(SystemExit):
        conf.multihost()  # rank out of range


def test_half_specified_cluster_coordinates_rejected(isolated_env):
    # --numProcesses without --coordinator must not silently run single-host
    # (it would double-train the stream and race checkpoint writers)
    conf = ConfArguments().parse(["--numProcesses", "2", "--processId", "0"])
    with pytest.raises(SystemExit):
        conf.multihost()


def test_float64_requires_cpu_backend(isolated_env):
    # --dtype float64 is the CPU verification dtype; TPU has no f64 path
    # and silently downcasting would make the flag lie (apps/common)
    from twtml_tpu.apps.common import select_backend

    conf = ConfArguments().parse(["--dtype", "float64"])
    with pytest.raises(SystemExit):
        select_backend(conf)  # backend auto: must demand --backend cpu


def test_default_wire_is_auto_resolving_by_regime(isolated_env):
    """r5 (VERDICT r4 #1a): the fast path is the default path — --wire
    auto (the default) resolves to the ragged device-hash wire (bench.py's
    exact wire) in every back-to-back regime. Wall-clock streaming keeps
    padded (the ragged units bucket is data-dependent, so it cannot
    pre-compile before a live stream starts — warmup_compile); --hashOn
    host keeps padded; explicit --wire always wins."""
    conf = ConfArguments()
    assert conf.wire == "auto"
    assert conf.hashOn == "device"
    assert conf.seconds == 5  # reference.conf default: wall-clock
    assert conf.effective_wire() == "padded"
    conf = ConfArguments().parse(["--seconds", "0"])
    assert conf.effective_wire() == "ragged"  # the throughput regime
    conf = ConfArguments().parse(["--seconds", "0", "--hashOn", "host"])
    assert conf.effective_wire() == "padded"
    conf = ConfArguments().parse(["--wire", "padded", "--seconds", "0"])
    assert conf.effective_wire() == "padded"
    conf = ConfArguments().parse(["--wire", "ragged"])
    assert conf.effective_wire() == "ragged"


def test_explicit_ragged_with_host_hash_rejected(isolated_env):
    from twtml_tpu.apps.common import build_source

    conf = ConfArguments().parse(["--wire", "ragged", "--hashOn", "host"])
    with pytest.raises(SystemExit, match="device-hash wire"):
        build_source(conf)


def test_recycle_flag_validation(isolated_env, tmp_path):
    """--recycleAfterMb needs --checkpointDir (recycle = checkpoint +
    re-exec); with one it constructs armed."""
    from twtml_tpu.apps.common import AppCheckpoint, ProcessRecycler

    totals = {"count": 0, "batches": 0}
    conf = ConfArguments().parse(["--recycleAfterMb", "4096"])
    ckpt = AppCheckpoint(conf, lambda: None, lambda s: None, totals)
    with pytest.raises(SystemExit, match="checkpointDir"):
        ProcessRecycler(conf, ckpt, totals)
    conf = ConfArguments().parse([
        "--recycleAfterMb", "4096", "--checkpointDir", str(tmp_path),
    ])
    ckpt = AppCheckpoint(
        conf, lambda: __import__("numpy").zeros(4), lambda s: None, totals
    )
    r = ProcessRecycler(conf, ckpt, totals)
    assert r.threshold == 4096
    r.check(at_boundary=True)  # far below threshold: no-op
