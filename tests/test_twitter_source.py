"""TwitterSource tests via connect_fn injection (no egress in CI)."""

import json
import time

import pytest

from twtml_tpu import config as cfg
from twtml_tpu.streaming.twitter import TwitterSource


def fake_stream():
    yield json.dumps({
        "text": "RT @x: hello world",
        "retweeted_status": {
            "text": "hello world",
            "retweet_count": 500,
            "user": {"followers_count": 10},
        },
    })
    yield ""  # keep-alive
    yield "not json"
    yield json.dumps({"delete": {"status": {"id": 1}}})  # notice, no text
    yield json.dumps({"text": "plain tweet", "user": {}})


def test_parses_and_skips_noise():
    src = TwitterSource({}, connect_fn=fake_stream)
    got = []
    src.start(got.append)
    deadline = time.time() + 2
    while not src.exhausted and time.time() < deadline:
        time.sleep(0.01)
    src.stop()
    assert len(got) == 2
    assert got[0].is_retweet and got[0].retweeted_status.retweet_count == 500
    assert got[1].text == "plain tweet"


def test_from_properties_requires_credentials(clean_properties):
    for k in list(cfg._SYSTEM_PROPERTIES):
        cfg._SYSTEM_PROPERTIES.pop(k)
    with pytest.raises(SystemExit) as exc:
        TwitterSource.from_properties()
    assert "credentials missing" in str(exc.value)


def test_from_properties_with_credentials(clean_properties):
    for k in ("consumerKey", "consumerSecret", "accessToken", "accessTokenSecret"):
        cfg.set_property("twitter4j.oauth." + k, "x" * 10)
    src = TwitterSource.from_properties(connect_fn=fake_stream)
    assert src.credentials["twitter4j.oauth.consumerKey"] == "x" * 10
