"""The driver contract: ``dryrun_multichip(n)`` must pass for every
n in {1, 2, 4, 8, 16} (VERDICT r3 #7 — only n=8 had recorded evidence).

The conftest pins THIS process's backend at 8 virtual CPU devices, and a
jax backend's device count is fixed at init — so each contract point runs
in a FRESH subprocess (the same way the driver and CI invoke it). n=16 is
the layout where divisibility bugs hide: the 2D branch builds an
8 data × 2 model mesh there."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("n", [1, 2, 4, 8, 16])
def test_dryrun_multichip_contract_point(n):
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)  # the dryrun sets its own device count
    proc = subprocess.run(
        [sys.executable, "-c",
         f"import __graft_entry__ as g; g.dryrun_multichip({n})"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert f"dryrun_multichip({n}): OK" in proc.stdout
