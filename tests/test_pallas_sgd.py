"""Pallas fused-SGD kernel parity (interpret mode on the CPU harness): the
VMEM-resident loop must produce the same weights/predictions as the XLA
sgd_inner_loop path for supported configurations."""

import numpy as np
import pytest

import jax.numpy as jnp

from twtml_tpu.features.batch import FeatureBatch
from twtml_tpu.models.sgd import make_sgd_train_step, zero_weights
from twtml_tpu.ops import pallas_sgd

RNG = np.random.default_rng(11)
F_TEXT = 60  # + 4 numeric = 64 → pads to 128 lanes


def make_batch(n=14, pad_to=16, tokens=6):
    token_idx = RNG.integers(0, F_TEXT, size=(pad_to, tokens)).astype(np.int32)
    token_val = RNG.integers(1, 3, size=(pad_to, tokens)).astype(np.float32)
    numeric = (RNG.normal(size=(pad_to, 4)) * 0.1).astype(np.float32)
    label = RNG.uniform(50, 900, size=(pad_to,)).astype(np.float32)
    mask = np.zeros((pad_to,), dtype=np.float32)
    mask[:n] = 1.0
    token_idx[n:] = 0
    token_val[n:] = 0
    numeric[n:] = 0
    label[n:] = 0
    return FeatureBatch(token_idx, token_val, numeric, label, mask)


def run_step(use_pallas, batch, **kw):
    import jax

    step = jax.jit(
        make_sgd_train_step(
            num_text_features=F_TEXT,
            num_iterations=kw.pop("num_iterations", 30),
            step_size=0.005,
            use_pallas=use_pallas,
            **kw,
        )
    )
    return step(zero_weights(F_TEXT), batch)


def test_supports_gating():
    assert pallas_sgd.padded_lanes(100) == 128
    assert pallas_sgd.padded_lanes(128) == 128
    assert pallas_sgd.supports(
        batch_rows=16, num_features=128, mini_batch_fraction=1.0, dtype=jnp.float32
    )
    assert pallas_sgd.supports(  # unaligned F pads internally
        batch_rows=16, num_features=100, mini_batch_fraction=1.0, dtype=jnp.float32
    )
    assert not pallas_sgd.supports(
        batch_rows=16, num_features=128, mini_batch_fraction=0.5, dtype=jnp.float32
    )
    assert not pallas_sgd.supports(  # over VMEM budget
        batch_rows=16, num_features=2**20, mini_batch_fraction=1.0, dtype=jnp.float32
    )


def test_pallas_matches_xla_loop():
    batch = make_batch()
    w_pl, out_pl = run_step(True, batch)
    w_xla, out_xla = run_step(False, batch)
    np.testing.assert_allclose(np.asarray(w_pl), np.asarray(w_xla),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(out_pl.predictions), np.asarray(out_xla.predictions), atol=1e-4
    )
    assert float(out_pl.mse) == pytest.approx(float(out_xla.mse), rel=1e-5)
    assert float(out_pl.count) == float(out_xla.count)


def test_pallas_l2_and_convergence_match():
    batch = make_batch()
    w_pl, _ = run_step(True, batch, l2_reg=0.05, convergence_tol=0.01)
    w_xla, _ = run_step(False, batch, l2_reg=0.05, convergence_tol=0.01)
    np.testing.assert_allclose(np.asarray(w_pl), np.asarray(w_xla),
                               rtol=1e-5, atol=1e-6)


def test_pallas_empty_batch_no_update():
    batch = make_batch(n=0)
    w_pl, out = run_step(True, batch)
    assert np.all(np.asarray(w_pl) == 0.0)
    assert float(out.count) == 0.0


def test_direct_kernel_call_shapes():
    x = RNG.normal(size=(16, 64)).astype(np.float32)
    y = RNG.normal(size=(16,)).astype(np.float32)
    m = np.ones((16,), np.float32)
    w0 = np.zeros((64,), np.float32)
    w, preds = pallas_sgd.fused_dense_sgd(
        jnp.asarray(x), jnp.asarray(y), jnp.asarray(m), jnp.asarray(w0),
        num_iterations=5, step_size=0.1,
    )
    assert w.shape == (64,)
    assert preds.shape == (16,)
    np.testing.assert_allclose(np.asarray(preds), 0.0, atol=1e-7)  # w0 = 0