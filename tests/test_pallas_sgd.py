"""Pallas fused-SGD reference kernel (interpret mode on the CPU harness):
the VMEM-resident loop must track the XLA sgd_inner_loop path within the
documented bf16-storage tolerance, honor the zeroed-padding contract, and
gate itself to configurations that actually fit scoped VMEM on hardware
(the round-1 kernel OOM'd on a real v5e at the flagship shape; the budget
model now reflects measured usage — see ops/pallas_sgd.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from twtml_tpu.features.batch import FeatureBatch
from twtml_tpu.models.sgd import make_sgd_train_step, zero_weights
from twtml_tpu.ops import pallas_sgd
from twtml_tpu.ops.sparse import densify_text

RNG = np.random.default_rng(11)
F_TEXT = 60  # + 4 numeric = 64 → pads to 128 lanes


def make_batch(n=14, pad_to=16, tokens=6):
    token_idx = RNG.integers(0, F_TEXT, size=(pad_to, tokens)).astype(np.int32)
    token_val = RNG.integers(1, 3, size=(pad_to, tokens)).astype(np.float32)
    numeric = (RNG.normal(size=(pad_to, 4)) * 0.1).astype(np.float32)
    label = RNG.uniform(50, 900, size=(pad_to,)).astype(np.float32)
    mask = np.zeros((pad_to,), dtype=np.float32)
    mask[:n] = 1.0
    token_idx[n:] = 0
    token_val[n:] = 0
    numeric[n:] = 0
    label[n:] = 0
    return FeatureBatch(token_idx, token_val, numeric, label, mask)


def dense_design(batch):
    x_text = densify_text(
        jnp.asarray(batch.token_idx), jnp.asarray(batch.token_val), F_TEXT
    )
    return jnp.concatenate(
        [x_text, jnp.asarray(batch.numeric, dtype=jnp.float32)], axis=1
    )


def xla_reference(batch, **kw):
    step = jax.jit(
        make_sgd_train_step(
            num_text_features=F_TEXT,
            num_iterations=kw.pop("num_iterations", 30),
            step_size=kw.pop("step_size", 0.005),
            round_predictions=False,
            **kw,
        )
    )
    return step(zero_weights(F_TEXT), batch)


@pytest.mark.parametrize("kw", [
    {},
    {"l2_reg": 0.1},
    {"num_iterations": 5},
    {"convergence_tol": 0.5},  # converges early; the freeze must match
])
def test_matches_xla_loop(kw):
    batch = make_batch()
    w_ref, out_ref = xla_reference(batch, **dict(kw))
    w_pal, preds = pallas_sgd.fused_dense_sgd(
        dense_design(batch),
        jnp.asarray(batch.label),
        jnp.asarray(batch.mask),
        zero_weights(F_TEXT),
        num_iterations=kw.get("num_iterations", 30),
        step_size=0.005,
        l2_reg=kw.get("l2_reg", 0.0),
        convergence_tol=kw.get("convergence_tol", 0.001),
    )
    # bf16 storage of the design matrix: integer bigram counts are exact,
    # the scaled numerics round — the documented ~1e-3 relative envelope
    np.testing.assert_allclose(w_pal, w_ref, rtol=2e-3, atol=2e-3)
    valid = batch.mask.astype(bool)
    np.testing.assert_allclose(
        np.asarray(preds)[valid],
        np.asarray(out_ref.predictions)[valid],
        rtol=2e-3, atol=2e-3,
    )


def test_padding_rows_do_not_leak():
    """The kernel has no mask ref: zeroed padding rows must contribute
    nothing. Same data, different pad_to → identical weights."""
    small = make_batch(n=14, pad_to=16)
    large = FeatureBatch(*(
        np.concatenate([np.asarray(f), np.zeros((16,) + f.shape[1:], f.dtype)])
        for f in small
    ))
    kw = dict(num_iterations=10, step_size=0.005)
    w_a, _ = pallas_sgd.fused_dense_sgd(
        dense_design(small), jnp.asarray(small.label), jnp.asarray(small.mask),
        zero_weights(F_TEXT), **kw)
    w_b, _ = pallas_sgd.fused_dense_sgd(
        dense_design(large), jnp.asarray(large.label), jnp.asarray(large.mask),
        zero_weights(F_TEXT), **kw)
    np.testing.assert_allclose(w_a, w_b, rtol=1e-6, atol=1e-7)


def test_masked_rows_zeroed_defensively():
    """Even if a caller hands unzeroed garbage in masked rows, the call
    masks features and labels before the kernel sees them."""
    batch = make_batch(n=14, pad_to=16)
    x = np.asarray(dense_design(batch))
    x_dirty = x.copy()
    x_dirty[14:] = np.nan  # NaN garbage: multiply-masking would poison all
    label_dirty = np.asarray(batch.label).copy()
    label_dirty[14:] = np.inf
    kw = dict(num_iterations=10, step_size=0.005)
    w_clean, _ = pallas_sgd.fused_dense_sgd(
        jnp.asarray(x), jnp.asarray(batch.label), jnp.asarray(batch.mask),
        zero_weights(F_TEXT), **kw)
    w_dirty, _ = pallas_sgd.fused_dense_sgd(
        jnp.asarray(x_dirty), jnp.asarray(label_dirty), jnp.asarray(batch.mask),
        zero_weights(F_TEXT), **kw)
    np.testing.assert_allclose(w_clean, w_dirty, rtol=1e-6, atol=1e-7)


def test_empty_batch_no_update():
    batch = make_batch(n=0)
    w, preds = pallas_sgd.fused_dense_sgd(
        dense_design(batch), jnp.asarray(batch.label), jnp.asarray(batch.mask),
        zero_weights(F_TEXT), num_iterations=10, step_size=0.005)
    assert np.all(np.asarray(w) == 0.0)
    np.testing.assert_allclose(np.asarray(preds), 0.0, atol=1e-7)


def test_supports_gating():
    assert pallas_sgd.padded_lanes(100) == 128
    assert pallas_sgd.padded_lanes(128) == 128
    assert pallas_sgd.supports(
        batch_rows=16, num_features=128, mini_batch_fraction=1.0,
        dtype=jnp.float32,
    )
    # the flagship operating point must fit the measured VMEM model
    assert pallas_sgd.supports(
        batch_rows=2048, num_features=1004, mini_batch_fraction=1.0,
        dtype=jnp.float32,
    )
    assert not pallas_sgd.supports(  # sampling unsupported
        batch_rows=16, num_features=128, mini_batch_fraction=0.5,
        dtype=jnp.float32,
    )
    assert not pallas_sgd.supports(  # over the scoped-VMEM budget
        batch_rows=4096, num_features=2**14, mini_batch_fraction=1.0,
        dtype=jnp.float32,
    )
    assert not pallas_sgd.supports(  # f32 weights only
        batch_rows=16, num_features=128, mini_batch_fraction=1.0,
        dtype=jnp.bfloat16,
    )


def test_vmem_estimate_is_the_gate():
    """The flagship shape must clear the scoped-VMEM limit with the matrix
    bytes accounted at bf16 plus vector-stripe overhead."""
    est = pallas_sgd._vmem_estimate(2048, 1024)
    assert 2 * 2048 * 1024 * 2 < est <= pallas_sgd.VMEM_LIMIT_BYTES
