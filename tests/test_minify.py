"""Asset minification (tools/jsminify.py — the reference's sbt-uglify
analog, web/build.sbt:25-39): minified assets must tokenize identically,
EXECUTE identically in the CI dashboard harness, and be served in place of
the originals when present."""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.jsdom import Harness  # noqa: E402
from tools.jsmini import parse, tokenize  # noqa: E402
from tools.jsminify import minify  # noqa: E402

ASSETS = os.path.join(REPO, "twtml_tpu", "web", "assets")
JS = os.path.join(ASSETS, "js")
ALL_JS = ["api.js", "chart.js", "index.js", "test.js"]


@pytest.mark.parametrize("name", ALL_JS)
def test_minified_assets_tokenize_identically(name):
    with open(os.path.join(JS, name), encoding="utf-8") as fh:
        src = fh.read()
    out = minify(src)  # self-verifies the token stream
    assert len(out) < len(src)
    parse(out)  # and still parses as a program


def test_asi_hazards_preserved():
    # line structure is preserved, so ASI semantics cannot change
    src = "function f() {\n  return\n  1;\n}\n"
    out = minify(src)
    assert "return\n1" in out  # the hazardous newline survives


def test_minified_dashboard_executes(tmp_path):
    """The REAL dashboard flow (index.html + api.js + chart.js + index.js)
    runs on the CI interpreter from the MINIFIED assets and updates the
    same counters."""
    minified = {}
    for name in ("api.js", "chart.js", "index.js"):
        with open(os.path.join(JS, name), encoding="utf-8") as fh:
            p = tmp_path / name
            p.write_text(minify(fh.read()))
            minified[name] = str(p)
    h = Harness([os.path.join(ASSETS, "index.html")])
    h.fetch_routes["/api/stats"] = {
        "jsonClass": "Stats", "count": 0, "batch": 0, "mse": 0,
        "realStddev": 0, "predStddev": 0,
    }
    h.fetch_routes["/api/series"] = []
    for name in ("api.js", "chart.js", "index.js"):
        h.load_script(minified[name])
    h.dom_content_loaded()
    h.ws.server_open()
    h.ws.server_message(json.dumps({
        "jsonClass": "Stats", "count": 42, "batch": 7, "mse": 123,
        "realStddev": 5, "predStddev": 6,
    }))
    assert h.el("count").text == "42"
    assert h.el("mse").text == "123"


def test_server_serves_min_js_when_present(tmp_path):
    """web/server.py prefers file.min.js — the dist's dashboard actually
    loads the minified bundle with unchanged URLs."""
    from pathlib import Path

    from twtml_tpu.web.server import Server

    (tmp_path / "js").mkdir()
    (tmp_path / "js" / "app.js").write_text("var  x = 1;\n")
    (tmp_path / "js" / "app.min.js").write_text("var x=1;\n")
    (tmp_path / "js" / "plain.js").write_text("var  y = 2;\n")
    server = Server()
    server._assets = Path(tmp_path)
    resp = server._static_file("js/app.js")
    assert resp.body == b"var x=1;\n"
    resp = server._static_file("js/plain.js")  # no .min.js: the original
    assert resp.body == b"var  y = 2;\n"
    resp = server._static_file("js/app.min.js")  # explicit .min.js works
    assert resp.body == b"var x=1;\n"
