"""Verified checkpoints (ISSUE 4 tentpole, part 3): per-array CRC32 + a
finite flag in the archive meta. save() quarantines non-finite weights
instead of rotating good history out of keep_last; restore() verifies
checksums/shape/dtype and falls back past corrupt or non-finite archives
with distinct warnings; malformed ckpt-* filenames never crash the step
parse (satellite regression)."""

import json
import os

import numpy as np
import pytest

from twtml_tpu.checkpoint import Checkpointer
from twtml_tpu.telemetry import metrics as _metrics


@pytest.fixture(autouse=True)
def clean_metrics():
    _metrics.reset_for_tests()
    yield
    _metrics.reset_for_tests()


def _weights(seed, shape=(32,)):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


# -- satellite: malformed filenames ------------------------------------------

def test_malformed_ckpt_names_are_tolerated(tmp_path):
    """Regression: a stray name matching the ckpt- prefix used to crash
    latest_step's int(...) parse."""
    ck = Checkpointer(str(tmp_path))
    ck.save(3, _weights(0))
    for stray in ("ckpt-backup.npz", "ckpt-.npz", "ckpt-12a4.npz",
                  "ckpt-000000000003.npz.orig", "ckpt-old-000000000002.npz"):
        (tmp_path / stray).write_bytes(b"not a checkpoint")
    assert ck.latest_step() == 3
    state, meta = ck.restore()
    np.testing.assert_array_equal(state, _weights(0))
    # and pruning ignores them too
    for step in (4, 5, 6, 7):
        ck.save(step, _weights(step))
    assert ck.latest_step() == 7
    assert (tmp_path / "ckpt-backup.npz").exists()


# -- CRC / shape / dtype verification ----------------------------------------

def _tamper(path, mutate):
    """Rewrite an archive with its arrays mutated but its META unchanged —
    the torn/bit-flipped-but-still-loadable case CRC exists for."""
    with np.load(path) as data:
        arrays = {k: data[k] for k in data.files}
    arrays = mutate(arrays)
    with open(path, "wb") as fh:
        np.savez(fh, **arrays)


def test_crc_mismatch_falls_back_to_older(tmp_path):
    ck = Checkpointer(str(tmp_path))
    ck.save(1, _weights(1))
    ck.save(2, _weights(2))

    def flip(arrays):
        w = arrays["w"].copy()
        w[5] += 1.0  # silent bit damage: still np.loads fine
        arrays["w"] = w
        return arrays

    _tamper(str(tmp_path / "ckpt-000000000002.npz"), flip)
    state, meta = ck.restore()
    assert meta["step"] == 1
    np.testing.assert_array_equal(state, _weights(1))
    assert _metrics.get_registry().counter(
        "checkpoint.restore_corrupt").snapshot() == 1


def test_shape_and_dtype_mismatch_fall_back(tmp_path):
    ck = Checkpointer(str(tmp_path))
    ck.save(1, _weights(1))
    ck.save(2, _weights(2))
    ck.save(3, _weights(3))
    _tamper(
        str(tmp_path / "ckpt-000000000003.npz"),
        lambda a: {**a, "w": a["w"][:16]},  # truncated write
    )
    _tamper(
        str(tmp_path / "ckpt-000000000002.npz"),
        lambda a: {**a, "w": a["w"].astype(np.float64)},
    )
    state, meta = ck.restore()
    assert meta["step"] == 1
    assert _metrics.get_registry().counter(
        "checkpoint.restore_corrupt").snapshot() == 2


def test_dict_state_verifies_per_array(tmp_path):
    ck = Checkpointer(str(tmp_path))
    ck.save(1, {"centers": _weights(1, (4, 8)), "counts": np.arange(4)})
    ck.save(2, {"centers": _weights(2, (4, 8)), "counts": np.arange(4)})

    def corrupt_one(arrays):
        c = arrays["w__centers"].copy()
        c[0, 0] = 999.0
        arrays["w__centers"] = c
        return arrays

    _tamper(str(tmp_path / "ckpt-000000000002.npz"), corrupt_one)
    state, meta = ck.restore()
    assert meta["step"] == 1
    np.testing.assert_array_equal(state["centers"], _weights(1, (4, 8)))


def test_missing_key_is_corrupt(tmp_path):
    ck = Checkpointer(str(tmp_path))
    ck.save(1, {"a": _weights(1), "b": _weights(2)})
    ck.save(2, {"a": _weights(3), "b": _weights(4)})
    _tamper(
        str(tmp_path / "ckpt-000000000002.npz"),
        lambda arrays: {k: v for k, v in arrays.items() if k != "w__b"},
    )
    state, meta = ck.restore()
    assert meta["step"] == 1


# -- non-finite quarantine ---------------------------------------------------

def test_nonfinite_save_quarantines_instead_of_overwriting(tmp_path):
    """THE keep_last poisoning scenario: a diverged model checkpointing on
    cadence would rotate every good archive out within N saves. Non-finite
    saves go to quarantine-* names restore never sees."""
    ck = Checkpointer(str(tmp_path), keep_last=3)
    ck.save(1, _weights(1))
    bad = _weights(9)
    bad[3] = np.nan
    for step in (2, 3, 4, 5):  # would have rotated step 1 out twice over
        path = ck.save(step, bad)
        assert os.path.basename(path).startswith("quarantine-")
    state, meta = ck.restore()
    assert meta["step"] == 1
    np.testing.assert_array_equal(state, _weights(1))
    assert ck.latest_step() == 1
    reg = _metrics.get_registry()
    assert reg.counter("checkpoint.quarantined").snapshot() == 4
    # the quarantined archives are preserved for postmortems
    assert len([n for n in os.listdir(tmp_path)
                if n.startswith("quarantine-")]) == 4


def test_inf_counts_as_nonfinite_and_int_arrays_are_fine(tmp_path):
    ck = Checkpointer(str(tmp_path))
    bad = _weights(1)
    bad[0] = np.inf
    assert os.path.basename(ck.save(1, bad)).startswith("quarantine-")
    # integer state is trivially finite
    path = ck.save(2, {"counts": np.arange(5, dtype=np.int64)})
    assert os.path.basename(path) == "ckpt-000000000002.npz"


def test_restore_skips_legacy_nonfinite_archives(tmp_path):
    """Archives written BEFORE the integrity meta existed: finiteness is
    recomputed at restore, so a pre-r7 diverged save is still skipped."""
    ck = Checkpointer(str(tmp_path))
    ck.save(1, _weights(1))
    # hand-write a legacy-format archive (no finite/arrays meta) with NaNs
    bad = _weights(2)
    bad[0] = np.nan
    meta = {"step": 2}
    with open(tmp_path / "ckpt-000000000002.npz", "wb") as fh:
        np.savez(fh, __meta__=np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8), w=bad)
    state, meta = ck.restore()
    assert meta["step"] == 1
    assert _metrics.get_registry().counter(
        "checkpoint.restore_nonfinite").snapshot() == 1


def test_legacy_finite_archive_still_restores(tmp_path):
    """Back-compat: pre-r7 archives carry no CRC meta and must restore."""
    w = _weights(4)
    meta = {"step": 9, "count": 123}
    with open(tmp_path / "ckpt-000000000009.npz", "wb") as fh:
        np.savez(fh, __meta__=np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8), w=w)
    state, got = Checkpointer(str(tmp_path)).restore()
    np.testing.assert_array_equal(state, w)
    assert got["count"] == 123


def test_unreadable_archive_still_falls_back(tmp_path):
    """The pre-r7 behavior (crash-during-write tolerance) is preserved."""
    ck = Checkpointer(str(tmp_path))
    ck.save(1, _weights(1))
    (tmp_path / "ckpt-000000000002.npz").write_bytes(b"torn write")
    state, meta = ck.restore()
    assert meta["step"] == 1
