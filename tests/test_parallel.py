"""Sharded-training tests on the 8-device virtual CPU mesh.

The key invariant: data-parallel and feature-sharded training must produce
the SAME weights and stats as the single-device fused step — sharding is an
execution detail, not a semantics change (the psum replaces treeAggregate
bit-for-bit up to float reduction order)."""

import numpy as np
import pytest

import jax

from twtml_tpu.features.batch import FeatureBatch
from twtml_tpu.models import StreamingLinearRegressionWithSGD
from twtml_tpu.parallel import ParallelSGDModel, make_mesh, shard_batch

RNG = np.random.default_rng(21)
F_TEXT = 64


def make_batch(n=30, pad_to=32, tokens=8):
    token_idx = RNG.integers(0, F_TEXT, size=(pad_to, tokens)).astype(np.int32)
    token_val = RNG.integers(1, 3, size=(pad_to, tokens)).astype(np.float32)
    numeric = RNG.normal(size=(pad_to, 4)).astype(np.float32) * 0.1
    label = RNG.uniform(50, 900, size=(pad_to,)).astype(np.float32)
    mask = np.zeros((pad_to,), dtype=np.float32)
    mask[:n] = 1.0
    token_idx[n:] = 0
    token_val[n:] = 0
    numeric[n:] = 0
    label[n:] = 0
    return FeatureBatch(token_idx, token_val, numeric, label, mask)


@pytest.fixture(scope="module")
def single_result():
    batch = make_batch()
    model = StreamingLinearRegressionWithSGD(
        num_text_features=F_TEXT, num_iterations=30, step_size=0.005
    )
    outs = [model.step(batch) for _ in range(3)]
    return batch, model.latest_weights, outs


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_data_parallel_matches_single_device(single_result):
    batch, w_single, outs_single = single_result
    mesh = make_mesh(num_data=8)
    model = ParallelSGDModel(
        mesh, num_text_features=F_TEXT, num_iterations=30, step_size=0.005
    )
    outs = [model.step(batch) for _ in range(3)]
    np.testing.assert_allclose(model.latest_weights, w_single, rtol=1e-4, atol=1e-6)
    for o_par, o_single in zip(outs, outs_single):
        assert float(o_par.count) == float(o_single.count)
        assert float(o_par.mse) == pytest.approx(float(o_single.mse), rel=1e-4)
        assert float(o_par.real_stdev) == pytest.approx(
            float(o_single.real_stdev), rel=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(o_par.predictions), np.asarray(o_single.predictions), atol=1e-4
        )


def test_data_parallel_two_shards(single_result):
    batch, w_single, _ = single_result
    mesh = make_mesh(num_data=2, devices=jax.devices()[:2])
    model = ParallelSGDModel(
        mesh, num_text_features=F_TEXT, num_iterations=30, step_size=0.005
    )
    for _ in range(3):
        model.step(batch)
    np.testing.assert_allclose(model.latest_weights, w_single, rtol=1e-4, atol=1e-6)


def test_feature_sharded_matches_single_device(single_result):
    batch, w_single, outs_single = single_result
    mesh = make_mesh(num_data=2, num_model=4)
    model = ParallelSGDModel(
        mesh, num_text_features=F_TEXT, num_iterations=30, step_size=0.005
    )
    outs = [model.step(batch) for _ in range(3)]
    np.testing.assert_allclose(model.latest_weights, w_single, rtol=1e-4, atol=1e-6)
    for o_par, o_single in zip(outs, outs_single):
        assert float(o_par.mse) == pytest.approx(float(o_single.mse), rel=1e-4)
        np.testing.assert_allclose(
            np.asarray(o_par.predictions), np.asarray(o_single.predictions), atol=1e-4
        )


def test_feature_sharded_sparse_large():
    """2^12 text dims sharded 4 ways — exercises the out-of-slice masking."""
    batch = make_batch()
    big_idx = (batch.token_idx.astype(np.int64) * 53) % (2**12)
    batch = batch._replace(token_idx=big_idx.astype(np.int32))
    mesh = make_mesh(num_data=2, num_model=4)
    par = ParallelSGDModel(
        mesh, num_text_features=2**12, num_iterations=10, step_size=0.005
    )
    single = StreamingLinearRegressionWithSGD(
        num_text_features=2**12, num_iterations=10, step_size=0.005
    )
    par.step(batch)
    single.step(batch)
    np.testing.assert_allclose(
        par.latest_weights, single.latest_weights, rtol=1e-4, atol=1e-7
    )


def test_indivisible_batch_raises():
    mesh = make_mesh(num_data=8)
    model = ParallelSGDModel(mesh, num_text_features=F_TEXT)
    bad = make_batch(n=5, pad_to=12)
    with pytest.raises(ValueError, match="not divisible"):
        model.step(bad)


def test_indivisible_features_raise():
    mesh = make_mesh(num_data=2, num_model=4)
    with pytest.raises(ValueError, match="not divisible"):
        ParallelSGDModel(mesh, num_text_features=30)


def test_shard_batch_placement():
    mesh = make_mesh(num_data=8)
    batch = make_batch()
    sharded = shard_batch(batch, mesh)
    assert sharded.label.sharding.spec == jax.sharding.PartitionSpec("data")


def test_logistic_data_parallel_matches_single_device():
    """The non-least-squares residual through the sharded step (VERDICT r1
    weak #1): sharded logistic == single-device logistic."""
    from twtml_tpu.models import StreamingLogisticRegressionWithSGD as LR

    batch = make_batch()
    batch = batch._replace(label=(batch.label > 400).astype(np.float32))
    single = LR(num_text_features=F_TEXT, num_iterations=30, step_size=0.1)
    mesh = make_mesh(num_data=8)
    par = ParallelSGDModel(
        mesh, num_text_features=F_TEXT, num_iterations=30, step_size=0.1,
        residual_fn=LR.residual_fn, prediction_fn=LR.prediction_fn,
        round_predictions=LR.round_predictions,
    )
    for _ in range(3):
        o_s, o_p = single.step(batch), par.step(batch)
        assert float(o_p.count) == float(o_s.count)
        np.testing.assert_allclose(
            np.asarray(o_p.predictions), np.asarray(o_s.predictions), atol=1e-5
        )
        assert float(o_p.mse) == pytest.approx(float(o_s.mse), abs=1e-5)
    np.testing.assert_allclose(
        par.latest_weights, single.latest_weights, rtol=1e-4, atol=1e-6
    )


def test_logistic_feature_sharded_matches_single_device():
    from twtml_tpu.models import StreamingLogisticRegressionWithSGD as LR

    batch = make_batch()
    batch = batch._replace(label=(batch.label > 400).astype(np.float32))
    single = LR(num_text_features=F_TEXT, num_iterations=20, step_size=0.1)
    mesh = make_mesh(num_data=2, num_model=4)
    par = ParallelSGDModel(
        mesh, num_text_features=F_TEXT, num_iterations=20, step_size=0.1,
        residual_fn=LR.residual_fn, prediction_fn=LR.prediction_fn,
        round_predictions=LR.round_predictions,
    )
    par.step(batch)
    single.step(batch)
    np.testing.assert_allclose(
        par.latest_weights, single.latest_weights, rtol=1e-4, atol=1e-6
    )


def test_kmeans_mesh_matches_single_device():
    """Sharded streaming k-means == unsharded: assignments, centers, and
    cluster weights (per-center psum is the only difference)."""
    from twtml_tpu.models.kmeans import StreamingKMeans

    pts = RNG.normal(size=(64, 2)).astype(np.float32) * np.array(
        [1.0, 5.0], np.float32
    )
    mask = np.ones((64,), np.float32)
    mask[60:] = 0.0

    def build(mesh):
        return (
            StreamingKMeans(mesh=mesh)
            .set_k(3)
            .set_half_life(5, "batches")
            .set_random_centers(2, 0.0)
        )

    single, par = build(None), build(make_mesh(num_data=8))
    for _ in range(4):
        a_s = single.update(pts, mask)
        a_p = par.update(pts, mask)
        np.testing.assert_array_equal(a_s, a_p)
    np.testing.assert_allclose(
        par.latest_centers, single.latest_centers, rtol=1e-5, atol=1e-7
    )
    np.testing.assert_allclose(
        np.asarray(par.cluster_weights), np.asarray(single.cluster_weights),
        rtol=1e-5,
    )


def test_kmeans_mesh_indivisible_rows_raise():
    from twtml_tpu.models.kmeans import StreamingKMeans

    km = StreamingKMeans(mesh=make_mesh(num_data=8)).set_k(2)
    km.set_random_centers(2, 0.0)
    with pytest.raises(ValueError, match="not divisible"):
        km.update(np.zeros((12, 2), np.float32))


def test_feature_sharded_2e18_unit_batch():
    """BASELINE config #4 at full scale on the mesh: 2^18 text dims sharded
    over 'model', fed the default wire format (raw units, device hashing),
    must match the single-device run up to float reduction order."""
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.streaming.sources import SyntheticSource

    statuses = list(
        SyntheticSource(total=64, seed=3, base_ms=1785320000000).produce()
    )
    feat = Featurizer(num_text_features=2**18, now_ms=1785320000000)
    batch = feat.featurize_batch_units(statuses, row_bucket=64, pre_filtered=True)
    mesh = make_mesh(num_data=4, num_model=2)
    par = ParallelSGDModel(
        mesh, num_text_features=2**18, num_iterations=5, step_size=0.005
    )
    single = StreamingLinearRegressionWithSGD(
        num_text_features=2**18, num_iterations=5
    )
    out = par.step(batch)
    out_single = single.step(batch)
    assert float(out.mse) == pytest.approx(float(out_single.mse), rel=1e-4)
    np.testing.assert_allclose(
        par.latest_weights, single.latest_weights, rtol=1e-4, atol=1e-7
    )
