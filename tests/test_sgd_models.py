"""Learner-core parity tests.

The numpy oracle below independently implements MLlib 1.6's
GradientDescent.runMiniBatchSGD semantics (per-iteration step stepSize/√i,
SimpleUpdater/SquaredL2Updater, convergence tolerance on successive weight
vectors) so the fused XLA step can be checked against it, plus the
predict-then-train ordering and masked statistics of the reference app
(LinearRegression.scala:53-86).
"""

import numpy as np
import pytest

from twtml_tpu.features.batch import FeatureBatch
from twtml_tpu.models import (
    StreamingKMeans,
    StreamingLinearRegressionWithSGD,
    StreamingLogisticRegressionWithSGD,
)

RNG = np.random.default_rng(7)
F_TEXT = 16
F = F_TEXT + 4


def random_batch(n=12, pad_to=16, tokens=6, label_scale=100.0):
    token_idx = RNG.integers(0, F_TEXT, size=(pad_to, tokens)).astype(np.int32)
    token_val = RNG.integers(1, 4, size=(pad_to, tokens)).astype(np.float32)
    numeric = RNG.normal(size=(pad_to, 4)).astype(np.float32) * 0.1
    label = (RNG.uniform(0.2, 1.0, size=(pad_to,)) * label_scale).astype(np.float32)
    mask = np.zeros((pad_to,), dtype=np.float32)
    mask[:n] = 1.0
    # zero out padding rows like the real featurizer does
    token_val[n:] = 0
    token_idx[n:] = 0
    numeric[n:] = 0
    label[n:] = 0
    return FeatureBatch(token_idx, token_val, numeric, label, mask)


def densify(batch):
    b = batch.token_idx.shape[0]
    X = np.zeros((b, F), dtype=np.float64)
    for i in range(b):
        for j in range(batch.token_idx.shape[1]):
            X[i, batch.token_idx[i, j]] += batch.token_val[i, j]
    X[:, F_TEXT:] = batch.numeric
    return X


def oracle_sgd(X, y, w0, num_iter, step, l2=0.0, tol=0.001):
    """Independent MLlib GradientDescent oracle (fraction 1.0)."""
    w = w0.astype(np.float64).copy()
    for i in range(1, num_iter + 1):
        diff = X @ w - y
        grad = X.T @ diff / len(y)
        eta = step / np.sqrt(i)
        w_new = w * (1.0 - eta * l2) - eta * grad
        converged = tol > 0 and np.linalg.norm(w_new - w) < tol * max(
            np.linalg.norm(w_new), 1.0
        )
        w = w_new
        if converged:
            break
    return w


def valid(batch):
    return batch.mask.astype(bool)


class TestLinearParity:
    def test_weights_match_oracle(self):
        batch = random_batch()
        model = StreamingLinearRegressionWithSGD(
            num_text_features=F_TEXT, num_iterations=50, step_size=0.005
        )
        model.step(batch)
        X = densify(batch)[valid(batch)]
        y = batch.label[valid(batch)].astype(np.float64)
        w_expect = oracle_sgd(X, y, np.zeros(F), 50, 0.005)
        np.testing.assert_allclose(model.latest_weights, w_expect, rtol=2e-4, atol=1e-6)

    def test_l2_regularization_matches_oracle(self):
        batch = random_batch()
        model = StreamingLinearRegressionWithSGD(
            num_text_features=F_TEXT, num_iterations=25, step_size=0.005, l2_reg=0.1
        )
        model.step(batch)
        X = densify(batch)[valid(batch)]
        y = batch.label[valid(batch)].astype(np.float64)
        w_expect = oracle_sgd(X, y, np.zeros(F), 25, 0.005, l2=0.1)
        np.testing.assert_allclose(model.latest_weights, w_expect, rtol=2e-4, atol=1e-6)

    def test_sparse_path_matches_dense_path(self):
        batch = random_batch()
        dense = StreamingLinearRegressionWithSGD(
            num_text_features=F_TEXT, num_iterations=20, step_size=0.005,
            use_sparse=False,
        )
        sparse = StreamingLinearRegressionWithSGD(
            num_text_features=F_TEXT, num_iterations=20, step_size=0.005,
            use_sparse=True,
        )
        out_d = dense.step(batch)
        out_s = sparse.step(batch)
        np.testing.assert_allclose(
            dense.latest_weights, sparse.latest_weights, rtol=1e-5, atol=1e-7
        )
        np.testing.assert_allclose(
            np.asarray(out_d.predictions), np.asarray(out_s.predictions), atol=1e-5
        )

    def test_predict_then_train_ordering(self):
        """First batch must be scored with the zero init weights."""
        batch = random_batch()
        model = StreamingLinearRegressionWithSGD(num_text_features=F_TEXT)
        out = model.step(batch)
        assert np.all(np.asarray(out.predictions) == 0.0)
        y = batch.label[valid(batch)]
        assert float(out.mse) == pytest.approx(float(np.mean(y.astype(np.float64) ** 2)), rel=1e-5)
        # and training did move the weights
        assert np.abs(model.latest_weights).sum() > 0

    def test_stats_match_numpy(self):
        batch = random_batch()
        model = StreamingLinearRegressionWithSGD(num_text_features=F_TEXT)
        model.step(batch)  # move off zero weights
        out = model.step(batch)
        y = batch.label[valid(batch)].astype(np.float64)
        X = densify(batch)[valid(batch)]
        # reproduce predictions with the pre-step weights: re-run oracle once
        w_before = oracle_sgd(X, y, np.zeros(F), 50, 0.005)
        preds = X @ w_before
        rounded = np.where(preds >= 0, np.floor(preds + 0.5), np.ceil(preds - 0.5))
        assert float(out.count) == len(y)
        assert float(out.mse) == pytest.approx(float(np.mean((y - rounded) ** 2)), rel=2e-3)
        assert float(out.real_stdev) == pytest.approx(float(np.std(y)), rel=1e-4)
        assert float(out.pred_stdev) == pytest.approx(float(np.std(rounded)), rel=2e-3)

    def test_empty_batch_no_update(self):
        batch = random_batch(n=0)
        model = StreamingLinearRegressionWithSGD(num_text_features=F_TEXT)
        out = model.step(batch)
        assert float(out.count) == 0.0
        assert np.all(model.latest_weights == 0.0)

    def test_padding_rows_do_not_leak(self):
        """Same valid rows, different padding sizes → same weights."""
        small = random_batch(n=8, pad_to=8)
        big = FeatureBatch(
            np.pad(small.token_idx, ((0, 24), (0, 0))),
            np.pad(small.token_val, ((0, 24), (0, 0))),
            np.pad(small.numeric, ((0, 24), (0, 0))),
            np.pad(small.label, (0, 24)),
            np.pad(small.mask, (0, 24)),
        )
        m1 = StreamingLinearRegressionWithSGD(num_text_features=F_TEXT)
        m2 = StreamingLinearRegressionWithSGD(num_text_features=F_TEXT)
        m1.step(small)
        m2.step(big)
        np.testing.assert_allclose(m1.latest_weights, m2.latest_weights, rtol=1e-6)

    def test_mini_batch_fraction_subsamples(self):
        batch = random_batch()
        model = StreamingLinearRegressionWithSGD(
            num_text_features=F_TEXT, num_iterations=10, mini_batch_fraction=0.5
        )
        out = model.step(batch)
        assert float(out.count) == batch.num_valid  # stats use the full batch
        assert np.abs(model.latest_weights).sum() > 0


class TestLogistic:
    def test_learns_separable_data(self):
        n, pad = 32, 32
        token_idx = np.zeros((pad, 2), dtype=np.int32)
        token_val = np.zeros((pad, 2), dtype=np.float32)
        labels = np.zeros((pad,), dtype=np.float32)
        for i in range(n):
            cls = i % 2
            labels[i] = cls
            token_idx[i, 0] = 1 if cls else 2
            token_val[i, 0] = 1.0
        batch = FeatureBatch(
            token_idx,
            token_val,
            np.zeros((pad, 4), np.float32),
            labels,
            np.ones((pad,), np.float32),
        )
        model = StreamingLogisticRegressionWithSGD(
            num_text_features=F_TEXT, num_iterations=100, step_size=1.0,
            convergence_tol=0.0,
        )
        for _ in range(5):
            out = model.step(batch)
        preds = np.asarray(out.predictions)
        assert np.mean(preds == labels) > 0.95
        assert set(np.unique(preds)).issubset({0.0, 1.0})


class TestStreamingKMeans:
    def test_centers_converge_to_cluster_means(self):
        pts = np.concatenate(
            [
                RNG.normal(loc=(0, 0), scale=0.05, size=(50, 2)),
                RNG.normal(loc=(10, 10), scale=0.05, size=(50, 2)),
            ]
        ).astype(np.float32)
        model = StreamingKMeans().set_k(2).set_initial_centers(
            [[1.0, 1.0], [9.0, 9.0]], [0.0, 0.0]
        )
        assign = model.update(pts)
        centers = model.latest_centers
        centers = centers[np.argsort(centers[:, 0])]
        np.testing.assert_allclose(centers[0], pts[:50].mean(0), atol=0.05)
        np.testing.assert_allclose(centers[1], pts[50:].mean(0), atol=0.05)
        assert len(np.unique(assign)) == 2

    def test_half_life_decay_factor(self):
        model = StreamingKMeans().set_half_life(5, "batches")
        assert model.decay_factor == pytest.approx(0.5 ** (1 / 5))

    def test_full_decay_forgets_history(self):
        """decayFactor=0 → centers become this batch's cluster means."""
        model = StreamingKMeans(k=1, decay_factor=0.0).set_initial_centers(
            [[100.0, 100.0]], [1000.0]
        )
        pts = np.array([[1.0, 1.0], [3.0, 3.0]], np.float32)
        model.update(pts)
        np.testing.assert_allclose(model.latest_centers[0], [2.0, 2.0], atol=1e-5)

    def test_predict(self):
        model = StreamingKMeans(k=2).set_initial_centers(
            [[0.0, 0.0], [10.0, 10.0]], [1.0, 1.0]
        )
        out = model.predict(np.array([[1.0, 0.0], [9.0, 9.0]], np.float32))
        assert out.tolist() == [0, 1]


def test_bfloat16_dtype_trains():
    """--dtype bfloat16 (MXU-native) must train: weights move, stats finite,
    and the loss trend matches the f32 run's direction on the same stream."""
    import jax.numpy as jnp
    import numpy as np

    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.streaming.sources import SyntheticSource

    statuses = list(SyntheticSource(total=512, seed=9).produce())
    feat = Featurizer(now_ms=1785320000000)
    curves = {}
    for dtype in (jnp.float32, jnp.bfloat16):
        model = StreamingLinearRegressionWithSGD(num_iterations=10, dtype=dtype)
        mses = []
        for i in range(0, 512, 128):
            batch = feat.featurize_batch_units(
                statuses[i : i + 128], row_bucket=128, pre_filtered=True
            )
            mses.append(float(model.step(batch).mse))
        assert np.isfinite(mses).all()
        assert np.abs(model.latest_weights).sum() > 0
        curves[str(jnp.dtype(dtype))] = mses
    # both precisions learn (progressive-validation MSE falls)
    assert curves["bfloat16"][-1] < curves["bfloat16"][0]
    assert curves["float32"][-1] < curves["float32"][0]
