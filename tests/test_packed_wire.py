"""The one-buffer wire format (features/batch.py PackedBatch): host pack →
device bitcast unpack must be bit-identical for every field and dtype the
batch types ship (uint8/uint16 units, int16/int32 indices, uint16 counts,
float32), and a model fed packed batches must produce bitwise-identical
trajectories to one fed the plain arrays — packing changes transfer count,
never semantics."""

import numpy as np

import jax
import jax.numpy as jnp

from twtml_tpu.features.batch import (
    FeatureBatch,
    UnitBatch,
    pack_batch,
    unpack_batch,
)
from twtml_tpu.features.featurizer import Featurizer
from twtml_tpu.models import StreamingLinearRegressionWithSGD
from twtml_tpu.streaming.sources import SyntheticSource


def unit_batch(ascii_only=True):
    rng = np.random.default_rng(0)
    dtype = np.uint8 if ascii_only else np.uint16
    units = rng.integers(32, 127 if ascii_only else 0x3FF, size=(16, 24)).astype(dtype)
    return UnitBatch(
        units,
        rng.integers(0, 24, size=(16,)).astype(np.int32),
        rng.normal(size=(16, 4)).astype(np.float32),
        rng.uniform(0, 1000, size=(16,)).astype(np.float32),
        (rng.uniform(size=(16,)) < 0.9).astype(np.float32),
    )


def feature_batch(narrow=True):
    rng = np.random.default_rng(1)
    idx_t = np.int16 if narrow else np.int32
    val_t = np.uint16 if narrow else np.float32
    return FeatureBatch(
        rng.integers(0, 1000, size=(16, 8)).astype(idx_t),
        rng.integers(0, 4, size=(16, 8)).astype(val_t),
        rng.normal(size=(16, 4)).astype(np.float32),
        rng.uniform(0, 1000, size=(16,)).astype(np.float32),
        np.ones((16,), np.float32),
    )


def assert_roundtrip(batch):
    packed = pack_batch(batch)
    # host roundtrip
    host = unpack_batch(packed.buffer, packed.layout)
    for a, b in zip(batch, host):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)
    # device roundtrip (bitcast path inside jit)
    dev = jax.jit(lambda buf: tuple(unpack_batch(buf, packed.layout)))(
        jnp.asarray(packed.buffer)
    )
    for a, b in zip(batch, dev):
        assert np.dtype(a.dtype) == np.dtype(b.dtype)
        np.testing.assert_array_equal(a, np.asarray(b))


def test_roundtrip_unit_ascii():
    assert_roundtrip(unit_batch(ascii_only=True))


def test_roundtrip_unit_wide():
    assert_roundtrip(unit_batch(ascii_only=False))


def test_roundtrip_feature_narrow():
    assert_roundtrip(feature_batch(narrow=True))


def test_roundtrip_feature_wide():
    assert_roundtrip(feature_batch(narrow=False))


def test_model_trajectory_bitwise_identical():
    """Real featurized stream through the flagship model: explicitly packed
    wire vs plain arrays — identical mse sequence and final weights, bit
    for bit."""
    statuses = list(SyntheticSource(total=96, seed=3, base_ms=1785320000000).produce())
    feat = Featurizer(now_ms=1785320000000)
    chunks = [statuses[i : i + 32] for i in range(0, 96, 32)]
    batches = [
        feat.featurize_batch_units(c, row_bucket=32, pre_filtered=True)
        for c in chunks
    ]

    m_packed = StreamingLinearRegressionWithSGD(num_iterations=10)
    m_plain = StreamingLinearRegressionWithSGD(num_iterations=10)
    for b in batches:
        out_p = m_packed.step(pack_batch(b))  # opt-in one-buffer wire
        out_q = m_plain.step(b)
        assert float(out_p.mse) == float(out_q.mse)
    np.testing.assert_array_equal(m_packed.latest_weights, m_plain.latest_weights)


def test_packed_ragged_round_trip_and_step():
    """RaggedUnitBatch packs into one buffer (row_len carried as static
    layout) and trains bit-identically to the unpacked form — the shipped
    --wire ragged transport (apps/common.FetchPipeline pack=True)."""
    import numpy as np

    from twtml_tpu.features.batch import (
        RaggedUnitBatch,
        pack_batch,
        unpack_batch,
    )
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.models import StreamingLinearRegressionWithSGD
    from twtml_tpu.streaming.sources import SyntheticSource

    statuses = list(
        SyntheticSource(total=64, seed=17, base_ms=1785320000000).produce()
    )
    feat = Featurizer(now_ms=1785320000000)
    rb = feat.featurize_batch_ragged(statuses, row_bucket=32, unit_bucket=64)
    pk = pack_batch(rb)
    back = unpack_batch(pk.buffer, pk.layout)
    assert isinstance(back, RaggedUnitBatch)
    assert back.row_len == rb.row_len
    for a, b in zip(
        (rb.units, rb.offsets, rb.numeric, rb.label, rb.mask),
        (back.units, back.offsets, back.numeric, back.label, back.mask),
    ):
        np.testing.assert_array_equal(a, b)
    assert pk.num_valid == rb.num_valid

    plain = StreamingLinearRegressionWithSGD(num_iterations=5)
    packed = StreamingLinearRegressionWithSGD(num_iterations=5)
    out_a = plain.step(rb)
    out_b = packed.step(pk)
    for fa, fb in zip(out_a, out_b):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))
    np.testing.assert_array_equal(plain.latest_weights, packed.latest_weights)
