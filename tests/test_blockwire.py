"""Zero-copy wire emitter (native/tweetjson.cpp parse_tweet_block_wire).

The wire parser emits the ragged wire's unit representation straight from
raw block bytes — uint8 units when every kept row is ASCII, uint16
otherwise. The parity law: every array it emits (units, offsets, numeric,
labels, ascii flags) must be byte-identical to BOTH the legacy C block
parser and the Python object path (json.loads → Status → featurize), across
the adversarial sweep below. The stale-library seam must degrade loudly to
the ParsedBlock path — never a ctypes AttributeError mid-stream.
"""

import json
import os

import numpy as np
import pytest

from twtml_tpu.features import Featurizer, Status, native
from twtml_tpu.features.blocks import merge_blocks
from twtml_tpu.streaming.sources import BlockReplayFileSource

DATA = os.path.join(os.path.dirname(__file__), "data", "tweets.jsonl")

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


def _blocks(path, wire, **kw):
    src = BlockReplayFileSource(str(path), wire=wire, **kw)
    return list(src.produce())


def _merged(path, wire, **kw):
    return merge_blocks(_blocks(path, wire, **kw))


def _object_batch(path, feat, **kw):
    with open(path, encoding="utf-8") as fh:
        statuses = [Status.from_json(json.loads(l)) for l in fh if l.strip()]
    return feat.featurize_batch_ragged(statuses, **kw)


def _assert_block_parity(legacy, wire):
    """Wire-parsed block == legacy block (units compared as code units —
    the wire block may carry them uint8)."""
    np.testing.assert_array_equal(legacy.numeric, wire.numeric)
    np.testing.assert_array_equal(legacy.offsets, wire.offsets)
    np.testing.assert_array_equal(legacy.ascii, wire.ascii)
    np.testing.assert_array_equal(
        legacy.units.astype(np.uint16), wire.units.astype(np.uint16)
    )
    # the narrow dtype IS the ascii metadata: uint8 iff every row ASCII
    if wire.rows:
        assert (wire.units.dtype == np.uint8) == bool(wire.ascii.all())


def _assert_ragged_equal(a, b):
    for f in ("units", "offsets", "numeric", "label", "mask"):
        np.testing.assert_array_equal(getattr(a, f), getattr(b, f))
        assert getattr(a, f).dtype == getattr(b, f).dtype
    assert a.row_len == b.row_len


def _write(tmp_path, objs, name="tweets.jsonl", ensure_ascii=True):
    path = tmp_path / name
    path.write_text(
        "\n".join(json.dumps(o, ensure_ascii=ensure_ascii) for o in objs)
        + "\n",
        encoding="utf-8",
    )
    return path


def _rt(text, count=500, **extra):
    rt = {"text": text, "retweet_count": count,
          "user": {"followers_count": 1, "favourites_count": 2,
                   "friends_count": 3},
          "timestamp_ms": "1785313333333"}
    rt.update(extra)
    return {"text": "RT", "retweeted_status": rt}


@pytest.fixture()
def feat():
    return Featurizer(now_ms=1785320000000)


# ---------------------------------------------------------------------------
# block-level parity: wire emitter vs legacy C parser vs object path


def test_fixture_parity_all_three_paths(feat):
    legacy = _merged(DATA, wire=False)
    wire = _merged(DATA, wire=True)
    _assert_block_parity(legacy, wire)
    obj = _object_batch(DATA, feat, row_bucket=16, unit_bucket=128)
    blk = feat.featurize_parsed_block(
        wire, row_bucket=16, unit_bucket=128, ragged=True
    )
    _assert_ragged_equal(obj, blk)


def test_ascii_corpus_is_narrow(feat, tmp_path):
    path = _write(tmp_path, [_rt(f"plain ascii tweet {i}") for i in range(64)])
    wire = _merged(path, wire=True)
    assert wire.units.dtype == np.uint8 and wire.rows == 64
    _assert_block_parity(_merged(path, wire=False), wire)
    # the ragged batch ships the SAME narrow dtype the legacy path would
    # have downcast to — bit-identical wire
    obj = _object_batch(str(path), feat, row_bucket=64, unit_bucket=64)
    blk = feat.featurize_parsed_block(
        wire, row_bucket=64, unit_bucket=64, ragged=True
    )
    assert blk.units.dtype == np.uint8
    _assert_ragged_equal(obj, blk)


@pytest.mark.parametrize("ensure_ascii", [True, False])
def test_non_ascii_widens_and_matches(feat, tmp_path, ensure_ascii):
    """Folds, é/İ (length-changing lower), CJK, raw + escaped surrogate
    pairs: the emitter widens mid-block and stays byte-identical."""
    objs = (
        [_rt(f"ascii prefix {i}") for i in range(5)]
        + [_rt("Ünïcödé ROW é"), _rt("İstanbul ẞharp"), _rt("火 🔥 emoji"),
           _rt("pair \U0001f600 astral")]
        + [_rt(f"ascii suffix {i}") for i in range(5)]
    )
    path = _write(tmp_path, objs, ensure_ascii=ensure_ascii)
    legacy = _merged(path, wire=False)
    wire = _merged(path, wire=True)
    assert wire.units.dtype == np.uint16  # widened
    _assert_block_parity(legacy, wire)
    obj = _object_batch(str(path), feat, row_bucket=16, unit_bucket=64)
    blk = feat.featurize_parsed_block(
        wire, row_bucket=16, unit_bucket=64, ragged=True
    )
    _assert_ragged_equal(obj, blk)


def test_escaped_surrogate_pairs_and_lone_surrogates(tmp_path):
    """\\uD83D\\uDE00 pairs and lone halves pass through as units, exactly
    like the legacy parser and the JVM view."""
    lines = [
        json.dumps(_rt("emoji")),
        # escaped pair + escaped lone surrogate, raw control escapes
        '{"text": "RT", "retweeted_status": {"text": '
        '"a\\ud83d\\ude00b\\ud800c\\n\\t", "retweet_count": 500, '
        '"user": {"followers_count": 1}}}',
    ]
    path = tmp_path / "sur.jsonl"
    path.write_text("\n".join(lines) + "\n", encoding="utf-8")
    legacy = _merged(path, wire=False)
    wire = _merged(path, wire=True)
    assert wire.rows == 2
    _assert_block_parity(legacy, wire)
    u = wire.units.astype(np.uint16)
    assert (u == 0xD83D).sum() == 1  # pair high half, kept as a half
    assert (u == 0xD800).sum() == 1  # lone half, kept as-is


def test_empty_text_and_full_text_fallback(feat, tmp_path):
    objs = [
        _rt(""),  # empty body
        {"text": "RT", "retweeted_status": {
            "full_text": "extended body only", "retweet_count": 400,
            "user": {"followers_count": 2}}},
        {"text": "RT", "retweeted_status": {
            "text": "", "full_text": "fallback body", "retweet_count": 500,
            "user": {"followers_count": 1}}},
        {"text": "RT", "retweeted_status": {
            "text": "short wins", "full_text": "long form",
            "retweet_count": 600, "user": {"followers_count": 1}}},
    ]
    path = _write(tmp_path, objs)
    legacy = _merged(path, wire=False)
    wire = _merged(path, wire=True)
    assert wire.rows == 4
    _assert_block_parity(legacy, wire)


def test_oversized_text_drops_line_wire_path(tmp_path):
    """The kMaxTextUnits wire bound: over-bound texts (text OR full_text,
    any duplicate occurrence) drop the line; exactly-at-bound rows keep."""
    from twtml_tpu.features.native import MAX_TEXT_UNITS

    over = _rt("a" * (MAX_TEXT_UNITS + 1))
    over_full = {"text": "RT", "retweeted_status": {
        "text": "tiny", "full_text": "b" * (MAX_TEXT_UNITS + 100),
        "retweet_count": 500, "user": {"followers_count": 1}}}
    at_bound = _rt("c" * MAX_TEXT_UNITS)
    path = _write(tmp_path, [_rt("ok"), over, over_full, at_bound, _rt("ok2")])
    legacy = _merged(path, wire=False)
    wire = _merged(path, wire=True)
    assert wire.rows == legacy.rows == 3
    _assert_block_parity(legacy, wire)
    assert int(np.diff(wire.offsets).max()) == MAX_TEXT_UNITS


def test_single_tweet_and_all_padding_blocks(feat, tmp_path):
    # single kept tweet
    one = _write(tmp_path, [_rt("only one")], name="one.jsonl")
    wire = _merged(one, wire=True)
    assert wire.rows == 1
    _assert_block_parity(_merged(one, wire=False), wire)
    batch = feat.featurize_parsed_block(
        wire, row_bucket=8, unit_bucket=32, ragged=True
    )
    assert batch.num_valid == 1
    # nothing passes the filter -> blocks with zero rows are never yielded,
    # and the empty featurize (warmup twin) still matches shapes
    none = _write(
        tmp_path,
        [{"text": "plain, not a retweet", "retweet_count": 5}],
        name="none.jsonl",
    )
    assert _blocks(none, wire=True) == []
    warm = feat.featurize_batch_ragged([], row_bucket=8, unit_bucket=32)
    import jax

    assert jax.tree_util.tree_structure(warm) == jax.tree_util.tree_structure(
        batch
    )


def test_row_over_uint16_units_takes_int32_offset_wire(feat):
    """The PR 3 gating rule on the new path: a block whose rebuilt row
    length exceeds 65,535 units cannot ship uint16 length deltas — the
    packed ragged wire falls back to int32 offsets, bit-identically.

    The C parser bounds rows at 4096 units, so a >65,535-unit row is
    hand-built (the gate is static in row_len, not sniffed from data)."""
    from twtml_tpu.features.batch import (
        offsets_narrow,
        pack_batch,
        unpack_batch,
    )
    from twtml_tpu.features.blocks import ParsedBlock

    n_units = (1 << 16) + 10
    block = ParsedBlock(
        np.array([[500, 1, 2, 3, 1785313333333]], np.int64),
        np.full((n_units,), ord("x"), np.uint16),
        np.array([0, n_units], np.int64),
        np.array([1], np.uint8),
    )
    rb = feat.featurize_parsed_block(
        block, row_bucket=8, unit_bucket=1 << 17, ragged=True
    )
    assert rb.row_len == 1 << 17 and not offsets_narrow(rb.row_len)
    packed = pack_batch(rb)
    assert packed.layout[2][2] == "i32"  # int32 offset wire, not u16 deltas
    back = unpack_batch(packed.buffer, packed.layout)
    for f in ("units", "offsets", "numeric", "label", "mask"):
        np.testing.assert_array_equal(getattr(rb, f), getattr(back, f))
    # and a normal wire-parsed block stays on the narrow delta wire
    small = _merged(DATA, wire=True)
    rb2 = feat.featurize_parsed_block(small, row_bucket=8, ragged=True)
    assert pack_batch(rb2).layout[2][2] == "u16delta"


def test_tiny_blocks_carry_across_chunks(feat, tmp_path):
    """block_bytes far below a line forces the consumed/carry logic through
    the wire parser (prescreen + early-stop included)."""
    objs = [_rt(f"carry line {i} with some length to it") for i in range(20)]
    objs.insert(7, _rt("wide row é to flip dtype mid-stream"))
    path = _write(tmp_path, objs, ensure_ascii=False)
    whole = _merged(path, wire=True)
    tiny = _merged(path, wire=True, block_bytes=64)
    np.testing.assert_array_equal(whole.numeric, tiny.numeric)
    np.testing.assert_array_equal(whole.offsets, tiny.offsets)
    np.testing.assert_array_equal(
        whole.units.astype(np.uint16), tiny.units.astype(np.uint16)
    )
    _assert_block_parity(_merged(path, wire=False), whole)


def test_mixed_dtype_blocks_merge_to_uint16(tmp_path):
    """A narrow block and a widened block from one stream merge to uint16
    with values preserved (numpy promotion) — batch boundaries can cut a
    stream anywhere."""
    ascii_path = _write(tmp_path, [_rt("plain")], name="a.jsonl")
    uni_path = _write(
        tmp_path, [_rt("wide é")], name="u.jsonl", ensure_ascii=False
    )
    a = _merged(ascii_path, wire=True)
    u = _merged(uni_path, wire=True)
    assert a.units.dtype == np.uint8 and u.units.dtype == np.uint16
    merged = merge_blocks([a, u])
    assert merged.units.dtype == np.uint16
    assert merged.rows == 2 and merged.ascii.tolist() == [1, 0]


def test_garbage_lines_counted_and_skipped(tmp_path):
    """Bad-line contract on the wire path: torn/garbled lines never crash
    and stay visible (counted) while kept rows match the legacy parser."""
    from twtml_tpu.telemetry import metrics as _metrics

    good = json.dumps(_rt("survivor"))
    lines = [good, "totally not json", "[1, 2]", good, '{"broken": ',
             good + "   "]
    path = tmp_path / "garbage.jsonl"
    path.write_text("\n".join(lines) + "\n", encoding="utf-8")
    _metrics.reset_for_tests()
    wire = _merged(path, wire=True)
    legacy = _merged(path, wire=False)
    assert wire.rows == legacy.rows == 3
    _assert_block_parity(legacy, wire)
    assert _metrics.get_registry().counter(
        "ingest.rows_dropped_parse"
    ).snapshot() > 0


def test_invalid_utf8_in_rt_text_drops_line(tmp_path):
    """Overlong encodings drop the line; UTF-8-encoded surrogates keep it
    (json.loads' surrogatepass view) — as in the legacy parser."""
    good = json.dumps(_rt("ok")).encode()
    overlong = (b'{"text": "RT", "retweeted_status": {"text": "x\xc0\xafy", '
                b'"retweet_count": 500, "user": {"followers_count": 1}}}')
    surrogate = (b'{"text": "RT", "retweeted_status": {"text": "x\xed\xa0\x80y", '
                 b'"retweet_count": 500, "user": {"followers_count": 1}}}')
    path = tmp_path / "badutf8.jsonl"
    path.write_bytes(good + b"\n" + overlong + b"\n" + surrogate + b"\n")
    wire = _merged(path, wire=True)
    legacy = _merged(path, wire=False)
    assert wire.rows == legacy.rows == 2
    _assert_block_parity(legacy, wire)
    assert (wire.units.astype(np.uint16) == 0xD800).sum() == 1


@pytest.mark.parametrize("ensure_ascii", [True, False])
def test_fuzzed_unicode_parity_wire_vs_legacy(tmp_path, ensure_ascii):
    """Seeded fuzz (shuffled keys, nested junk, BMP/astral/controls) must
    parse identically through the wire emitter and the legacy parser."""
    import random

    rng = random.Random(20260804 + int(ensure_ascii))
    alphabet = (
        [chr(c) for c in range(0x20, 0x7F)]
        + ["\n", "\t", "\r", "\b", "\f"]
        + [chr(rng.randrange(0xA0, 0x2FFF)) for _ in range(40)]
        + ["é", "你", "İ", "ẞ", "\U0001f600", "\U0001f525"]
    )

    def shuffled(d):
        items = list(d.items())
        rng.shuffle(items)
        return {k: shuffled(v) if isinstance(v, dict) else v for k, v in items}

    objs = []
    for i in range(200):
        text = "".join(
            rng.choice(alphabet) for _ in range(rng.randrange(0, 60))
        )
        objs.append(shuffled({
            "text": "RT wrap",
            "junk": {"nested": [i, None, True, {"deep": [text]}]},
            f"unknown_{rng.randrange(10)}": rng.choice([None, True, 1.5, "s"]),
            "retweeted_status": {
                "text": text,
                "retweet_count": rng.randrange(0, 2000),
                "extra": {"a": [rng.randrange(9)]},
                "user": {
                    "followers_count": rng.randrange(0, 10**9),
                    "favourites_count": rng.randrange(0, 10**6),
                    "friends_count": rng.randrange(0, 10**5),
                    "screen_name": "user_" + str(i),
                },
                "timestamp_ms": str(rng.randrange(10**12, 2 * 10**12)),
            },
        }))
    path = _write(tmp_path, objs, ensure_ascii=ensure_ascii)
    legacy = _merged(path, wire=False)
    wire = _merged(path, wire=True)
    assert legacy.rows > 20
    _assert_block_parity(legacy, wire)


def test_duplicate_keys_match_legacy(tmp_path):
    """Duplicate text/retweeted_status occurrences: the wire parser keeps
    the legacy any-occurrence capping and last-content-wins rules."""
    dup_text = (
        '{"text": "RT", "retweeted_status": {"text": "first", '
        '"text": "last wins", "retweet_count": 500, '
        '"user": {"followers_count": 7}}}'
    )
    dup_rt = (
        '{"text": "RT", "retweeted_status": {"text": "one", '
        '"retweet_count": 500}, "retweeted_status": {"text": "two", '
        '"retweet_count": 600, "user": {"followers_count": 9}}}'
    )
    oversized_first = (
        '{"text": "RT", "retweeted_status": {"text": "'
        + "d" * 4097
        + '", "text": "small", "retweet_count": 500, '
        '"user": {"followers_count": 1}}}'
    )
    path = tmp_path / "dups.jsonl"
    path.write_text(
        "\n".join([dup_text, dup_rt, oversized_first]) + "\n",
        encoding="utf-8",
    )
    legacy = _merged(path, wire=False)
    wire = _merged(path, wire=True)
    _assert_block_parity(legacy, wire)
    assert wire.rows == 2  # the oversized-duplicate line dropped


def test_unit_labels_accept_narrow_blocks(tmp_path):
    """sentiment_labels_from_units (unit_label_fn) must score uint8 narrow
    blocks identically to the uint16 legacy blocks."""
    from twtml_tpu.features.sentiment import sentiment_labels_from_units

    path = _write(
        tmp_path,
        [_rt("good happy day"), _rt("bad sad loss"), _rt("neutral words")],
    )
    legacy = _merged(path, wire=False)
    wire = _merged(path, wire=True)
    assert wire.units.dtype == np.uint8
    np.testing.assert_array_equal(
        sentiment_labels_from_units(wire.units, wire.offsets),
        sentiment_labels_from_units(legacy.units, legacy.offsets),
    )


def test_padded_wire_from_narrow_block(feat, tmp_path):
    """ragged=False on a uint8 block: the pad path widens once and matches
    the object path (the emitter targets the ragged wire, but a padded
    consumer must not read garbage)."""
    path = _write(tmp_path, [_rt(f"padded path {i}") for i in range(4)])
    wire = _merged(path, wire=True)
    assert wire.units.dtype == np.uint8
    blk = feat.featurize_parsed_block(wire, row_bucket=8, unit_bucket=32)
    with open(path, encoding="utf-8") as fh:
        statuses = [Status.from_json(json.loads(l)) for l in fh if l.strip()]
    obj = feat.featurize_batch_units(statuses, row_bucket=8, unit_bucket=32)
    for f in ("units", "length", "numeric", "label", "mask"):
        np.testing.assert_array_equal(getattr(obj, f), getattr(blk, f))


def test_normalize_accents_on_narrow_block(tmp_path):
    """normalize_accents marks every row redo: a uint8 block must widen for
    the Unicode round-trip instead of mis-decoding."""
    feat = Featurizer(now_ms=1785320000000, normalize_accents=True)
    path = _write(tmp_path, [_rt("cafe latte plain")])
    wire = _merged(path, wire=True)
    assert wire.units.dtype == np.uint8
    batch = feat.featurize_parsed_block(
        wire, row_bucket=8, unit_bucket=32, ragged=True
    )
    with open(path, encoding="utf-8") as fh:
        statuses = [Status.from_json(json.loads(l)) for l in fh if l.strip()]
    obj = feat.featurize_batch_ragged(statuses, row_bucket=8, unit_bucket=32)
    # values must match unit for unit; the block path conservatively keeps
    # the WIDE wire under normalize_accents (redo marks every row — the
    # pre-existing rule, featurize_parsed_block) while the object path can
    # re-check isascii post-strip, so dtypes may differ in this uncommon
    # config (wire representation only; the device hash upcasts either way)
    for f in ("units", "offsets", "numeric", "label", "mask"):
        np.testing.assert_array_equal(
            np.asarray(getattr(obj, f), dtype=np.float64),
            np.asarray(getattr(batch, f), dtype=np.float64),
        )
    assert obj.row_len == batch.row_len


# ---------------------------------------------------------------------------
# the stale-library degrade seam (features/native.py)


def test_wire_missing_degrades_to_legacy_parser(tmp_path, monkeypatch):
    """A library without the wire symbol: parse_tweet_block_wire returns
    None, the block source falls back to the legacy parser, and the batches
    keep flowing — no AttributeError mid-stream."""
    monkeypatch.setattr(native, "_wire_missing", True)
    assert native.parse_tweet_block_wire(b'{"a":1}\n', 100, 1000) is None
    assert not native.wire_available()
    wire_requested = _merged(DATA, wire=True)  # silently legacy-parsed
    legacy = _merged(DATA, wire=False)
    assert wire_requested.units.dtype == np.uint16
    _assert_block_parity(legacy, wire_requested)


def test_bind_wire_flags_missing_symbol_and_counts(monkeypatch):
    """_bind_wire on a symbol-less library object: non-strict flags the
    degrade (warning + native.wire_degraded counter), strict raises so
    get_lib's rebuild path can kick in."""
    from twtml_tpu.telemetry import metrics as _metrics

    class _NoWire:
        def __getattr__(self, name):
            raise AttributeError(name)

    _metrics.reset_for_tests()
    monkeypatch.setattr(native, "_wire_missing", False)
    with pytest.raises(AttributeError):
        native._bind_wire(_NoWire(), strict=True)
    native._bind_wire(_NoWire(), strict=False)
    assert native._wire_missing
    assert _metrics.get_registry().counter(
        "native.wire_degraded"
    ).snapshot() == 1
    # restore the real binding for the rest of the session
    monkeypatch.setattr(native, "_wire_missing", False)


def test_stale_library_without_wire_symbol_loads_degraded(tmp_path):
    """End-to-end seam: an actual .so missing parse_tweet_block_wire loads
    with strict=False, flags the degrade, and keeps the OLD symbols
    callable (the ParsedBlock path stays native, not Python)."""
    import subprocess

    src = tmp_path / "stale.cpp"
    # a minimal stale lib: every pre-wire symbol present (as stubs), no
    # parse_tweet_block_wire
    src.write_text(
        """
#include <cstdint>
extern "C" {
int32_t fasthash_batch(uint16_t*, int64_t*, int32_t, int32_t, int32_t,
                       int32_t*, float*, int32_t*, int32_t) { return 0; }
int32_t pad_units_batch(uint16_t*, int64_t*, int32_t, int32_t, int32_t,
                        int32_t, uint16_t*, int32_t*) { return 0; }
int32_t pad_units_batch_u8(uint16_t*, int64_t*, int32_t, int32_t, int32_t,
                           int32_t, uint8_t*, int32_t*) { return 0; }
void lexicon_score_batch(uint16_t*, int64_t*, int32_t, uint16_t*, int64_t*,
                         int32_t*, int32_t, uint16_t*, int64_t*, int32_t*,
                         int32_t, int32_t*, uint8_t*) {}
int64_t parse_tweet_block(const char*, int64_t, int64_t, int64_t, int64_t,
                          int64_t, int64_t*, uint16_t*, int64_t*, uint8_t*,
                          int64_t* c, int64_t* b) { *c = 0; *b = 0; return 0; }
}
""",
        encoding="utf-8",
    )
    so = tmp_path / "stale.so"
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", "-o", str(so), str(src)],
        check=True, capture_output=True,
    )
    saved = native._wire_missing
    try:
        with pytest.raises(AttributeError):
            native._load(str(so), strict=True)
        lib = native._load(str(so), strict=False)
        assert native._wire_missing
        assert lib.parse_tweet_block is not None  # old symbols still bound
    finally:
        native._wire_missing = saved
        # re-evaluate EVERY degrade flag against the real library: the
        # degraded _load above also flagged the r15/r17/r18 symbols this
        # stale lib lacks, and restoring only _wire_missing left those
        # fast paths silently off for the rest of the suite
        native.rebind_flags()


# ---------------------------------------------------------------------------
# metrics + app-level parity


def test_parse_metrics_published(tmp_path):
    from twtml_tpu.telemetry import metrics as _metrics

    _metrics.reset_for_tests()
    _merged(DATA, wire=True)
    reg = _metrics.get_registry()
    assert reg.counter("ingest.parse_bytes").snapshot() >= os.path.getsize(
        DATA
    )
    assert reg.gauge("ingest.parse_tweets_per_s").snapshot() > 0


def test_linear_app_wire_matches_legacy_block(tmp_path, capsys):
    """End to end through the CLI run() in the back-to-back ragged regime
    (where --blockWire auto engages): --blockWire on == --blockWire off ==
    --ingest object, stat line for stat line."""
    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.config import ConfArguments

    outputs = {}
    for name, args in (
        ("object", ["--ingest", "object"]),
        ("block-legacy", ["--ingest", "block", "--blockWire", "off"]),
        ("block-wire", ["--ingest", "block", "--blockWire", "on"]),
    ):
        conf = ConfArguments().parse([
            "--source", "replay", "--replayFile", DATA, "--seconds", "0",
            "--batchBucket", "16", "--tokenBucket", "128",
            "--lightning", "http://127.0.0.1:9",
            "--twtweb", "http://127.0.0.1:9", "--webTimeout", "0.2",
            "--backend", "cpu", "--master", "local[1]", *args,
        ])
        assert conf.effective_wire() == "ragged"
        app.run(conf, max_batches=1)
        outputs[name] = [
            l for l in capsys.readouterr().out.splitlines()
            if l.startswith("count:")
        ]
    assert outputs["block-wire"] == outputs["block-legacy"] == outputs["object"]
    assert outputs["block-wire"], "no stats lines captured"
