"""Model & data observability plane (ISSUE 8): the in-step quality vector,
the host-side drift/trend watcher, and the web/checkpoint surfaces.

The laws under test, in the order the ISSUE states them:
- **zero added fetches / zero added collectives** with ``--modelWatch on``
  — asserted by COUNTING ``jax.device_get`` / ``process_allgather`` over a
  real app run and a real lockstep run (the PR 1/5 idiom);
- **off bit-parity**: the ``--modelWatch off`` step's output pytree is
  structurally the pre-quality (HEAD) program's, and the quality plane is
  observation-only — ON vs OFF weights, stats, and predictions bit-equal;
- **drift detection**: an injected synthetic feature/label shift alerts, a
  stationary stream stays ok (deterministic seeded series);
- **per-tenant quality == standalone-model quality** at M=4 (the tenant
  plane's lax.map bit-parity law extended to the new leaf);
- **checkpoint quality stamp** roundtrip + ``tools/model_report.py`` exit
  codes (0 well-formed, 2 malformed);
- the ``/api/model`` endpoint and the ModelHealth wire type.
"""

import json
import math
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools import model_report  # noqa: E402
from twtml_tpu.config import ConfArguments  # noqa: E402
from twtml_tpu.features.featurizer import Featurizer  # noqa: E402
from twtml_tpu.models import (  # noqa: E402
    StepOutput,
    StreamingLinearRegressionWithSGD,
)
from twtml_tpu.ops.quality import (  # noqa: E402
    QUALITY_FIELDS,
    QUALITY_INDEX,
    QUALITY_WIDTH,
)
from twtml_tpu.streaming.sources import SyntheticSource  # noqa: E402
from twtml_tpu.telemetry import metrics as _metrics  # noqa: E402
from twtml_tpu.telemetry import modelwatch as modelwatch_mod  # noqa: E402
from twtml_tpu.telemetry import tenants as _tenants_tel  # noqa: E402
from twtml_tpu.telemetry.modelwatch import ModelWatch  # noqa: E402

NOW_MS = 1785320000000


@pytest.fixture(autouse=True)
def _fresh_state():
    _metrics.reset_for_tests()
    modelwatch_mod.reset_for_tests()
    _tenants_tel.reset_for_tests()
    yield
    _metrics.reset_for_tests()
    modelwatch_mod.reset_for_tests()
    _tenants_tel.reset_for_tests()


def _ragged_batches(n=256, b=128, seed=3):
    feat = Featurizer(now_ms=NOW_MS)
    statuses = list(SyntheticSource(total=n, seed=seed).produce())
    return [
        feat.featurize_batch_ragged(
            statuses[i : i + b], row_bucket=b, pre_filtered=True
        )
        for i in range(0, n, b)
    ]


# ---------------------------------------------------------------------------
# the in-step quality vector


def test_quality_vector_shape_fields_and_ranges():
    model = StreamingLinearRegressionWithSGD(quality=True)
    out = model.step(_ragged_batches()[0])
    q = np.asarray(out.quality)
    assert q.shape == (QUALITY_WIDTH,)
    assert q.dtype == np.float32
    assert np.isfinite(q).all()
    assert len(QUALITY_FIELDS) == QUALITY_WIDTH
    # norms are non-negative; first batch from zero weights:
    # ||w_new|| == ||w_new - 0||
    assert q[QUALITY_INDEX["weight_norm"]] == pytest.approx(
        q[QUALITY_INDEX["update_norm"]]
    )
    assert q[QUALITY_INDEX["grad_norm"]] > 0
    # occupancy is a fraction of folded bins; top share a mass fraction
    assert 0.0 <= q[QUALITY_INDEX["bucket_occupancy"]] <= 1.0
    assert 0.0 < q[QUALITY_INDEX["bucket_top_share"]] <= 1.0
    # label moments match the host's masked computation
    rb = _ragged_batches()[0]
    valid = np.asarray(rb.mask) > 0
    labels = np.asarray(rb.label, np.float64)[valid]
    assert q[QUALITY_INDEX["label_mean"]] == pytest.approx(
        labels.mean(), rel=1e-5
    )
    assert q[QUALITY_INDEX["label_var"]] == pytest.approx(
        labels.var(), rel=1e-4
    )


def test_off_program_is_structurally_head_and_observation_only():
    """ACCEPTANCE (off bit-parity): quality=False leaves the output pytree
    the HEAD 5-leaf StepOutput (the quality leaf is None — same compiled
    program structure), and the quality computation is a pure side channel:
    ON vs OFF weights, stats, and predictions are byte-identical."""
    import jax

    off = StreamingLinearRegressionWithSGD()
    on = StreamingLinearRegressionWithSGD(quality=True)
    batches = _ragged_batches()
    for rb in batches:
        o_off, o_on = off.step(rb), on.step(rb)
        assert o_off.quality is None
        assert o_on.quality is not None
        for f in ("count", "mse", "real_stdev", "pred_stdev"):
            assert np.asarray(getattr(o_off, f)).tobytes() == (
                np.asarray(getattr(o_on, f)).tobytes()
            ), f
        assert np.array_equal(
            np.asarray(o_off.predictions), np.asarray(o_on.predictions)
        )
    assert off.latest_weights.tobytes() == on.latest_weights.tobytes()
    # structural differential: the OFF output pytree has exactly the HEAD
    # leaf set; ON appends exactly one [QUALITY_WIDTH] leaf
    leaves_off = jax.tree_util.tree_leaves(off.step(batches[0]))
    leaves_on = jax.tree_util.tree_leaves(on.step(batches[0]))
    assert len(leaves_on) == len(leaves_off) + 1


def test_quality_rides_the_superbatch_scan():
    model = StreamingLinearRegressionWithSGD(quality=True)
    seq = StreamingLinearRegressionWithSGD(quality=True)
    from twtml_tpu.features.batch import stack_batches

    batches = _ragged_batches()
    outs = model.step_many(stack_batches(batches))
    q = np.asarray(outs.quality)
    assert q.shape == (len(batches), QUALITY_WIDTH)
    # the scanned program's per-batch quality bit-equals sequential steps
    for k, rb in enumerate(batches):
        ok = seq.step(rb)
        assert np.asarray(ok.quality).tobytes() == q[k].tobytes(), k


def test_mesh_quality_is_global_and_finite():
    import jax

    from twtml_tpu.parallel import ParallelSGDModel, make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    mesh = make_mesh(num_data=2, devices=jax.devices()[:2])
    pm = ParallelSGDModel(mesh, quality=True)
    single = StreamingLinearRegressionWithSGD(quality=True)
    rb = _ragged_batches()[0]
    qm = np.asarray(pm.step(rb).quality)
    qs = np.asarray(single.step(rb).quality)
    assert qm.shape == (QUALITY_WIDTH,)
    assert np.isfinite(qm).all()
    # psum-global moments match the single-device values (same math,
    # different reduction association)
    for f in ("label_mean", "label_var", "num_mean_0", "bucket_occupancy"):
        i = QUALITY_INDEX[f]
        assert qm[i] == pytest.approx(float(qs[i]), rel=1e-4), f


def test_m4_per_tenant_quality_bit_equals_standalone():
    """ACCEPTANCE: tenant m's quality vector bit-equals a standalone
    single-tenant model's on the routed sub-batches (the lax.map parity
    law extended to the new leaf)."""
    from twtml_tpu.features.batch import split_batch_tenants, tenant_route_keys
    from twtml_tpu.parallel import TenantStackModel

    m = 4
    mt = TenantStackModel(m, step_size=0.1, quality=True)
    singles = [
        StreamingLinearRegressionWithSGD(step_size=0.1, quality=True)
        for _ in range(m)
    ]
    for rb in _ragged_batches():
        parts = split_batch_tenants(rb, tenant_route_keys(rb, m), m)
        out = mt.step(rb)
        q = np.asarray(out.quality)
        assert q.shape == (m, QUALITY_WIDTH)
        for i in range(m):
            oi = singles[i].step(parts[i])
            assert np.asarray(oi.quality).tobytes() == q[i].tobytes(), i


# ---------------------------------------------------------------------------
# the drift / loss-trend detector (deterministic synthetic streams)


def _qvec(rng, label_mean=100.0, num0=5.0, weight_norm=50.0):
    q = np.zeros(QUALITY_WIDTH, np.float64)
    q[QUALITY_INDEX["weight_norm"]] = weight_norm + rng.normal(0, 0.5)
    q[QUALITY_INDEX["update_norm"]] = 1.0 + rng.normal(0, 0.1)
    q[QUALITY_INDEX["grad_norm"]] = 200.0 + rng.normal(0, 5.0)
    q[QUALITY_INDEX["pred_mean"]] = label_mean + rng.normal(0, 1.0)
    q[QUALITY_INDEX["pred_var"]] = 25.0
    q[QUALITY_INDEX["label_mean"]] = label_mean + rng.normal(0, 1.0)
    q[QUALITY_INDEX["label_var"]] = 25.0
    q[QUALITY_INDEX["resid_mean"]] = rng.normal(0, 0.5)
    q[QUALITY_INDEX["resid_var"]] = 4.0
    q[QUALITY_INDEX["num_mean_0"]] = num0 + rng.normal(0, 0.1)
    q[QUALITY_INDEX["bucket_occupancy"]] = 0.9
    q[QUALITY_INDEX["bucket_top_share"]] = 0.1 + rng.normal(0, 0.005)
    return q


def test_stationary_stream_stays_ok():
    rng = np.random.default_rng(7)
    watch = ModelWatch()
    for _ in range(300):
        v = watch.observe(_qvec(rng), 128.0, 100.0 + rng.normal(0, 2.0))
        assert v["level"] == "ok", v
    assert v["drift_score"] < modelwatch_mod.WARN_Z
    assert abs(v["loss_trend"]) < modelwatch_mod.TREND_WARN
    assert _metrics.get_registry().counter(
        "model.drift_episodes"
    ).snapshot() == 0


def test_injected_label_shift_alerts():
    """ACCEPTANCE: a 20σ label/prediction mean shift mid-stream crosses the
    alert threshold within one recent window; the episode is counted and
    the flight recorder sees the flip."""
    from twtml_tpu.telemetry import blackbox as blackbox_mod

    rec = blackbox_mod.install(config={"t": 1})
    try:
        rng = np.random.default_rng(7)
        watch = ModelWatch()
        for _ in range(150):
            v = watch.observe(_qvec(rng), 128.0, 100.0)
            assert v["level"] == "ok"
        levels = []
        for _ in range(modelwatch_mod.RECENT_WINDOW + 2):
            v = watch.observe(
                _qvec(rng, label_mean=120.0), 128.0, 100.0
            )
            levels.append(v["level"])
        assert levels[-1] == "alert", levels
        assert v["drift_score"] >= modelwatch_mod.ALERT_Z
        reg = _metrics.get_registry()
        assert reg.counter("model.drift_episodes").snapshot() >= 1
        assert reg.gauge("model.health_level").snapshot() == 2
        kinds = [e["kind"] for e in rec.bundle("t")["events"]]
        assert "model_health" in kinds and "drift_episode" in kinds
    finally:
        blackbox_mod.uninstall()


def test_feature_shift_alerts_via_numeric_moment():
    rng = np.random.default_rng(11)
    watch = ModelWatch()
    for _ in range(150):
        watch.observe(_qvec(rng), 128.0, 100.0)
    for _ in range(modelwatch_mod.RECENT_WINDOW + 2):
        v = watch.observe(_qvec(rng, num0=9.0), 128.0, 100.0)
    assert v["level"] == "alert"


def test_loss_trend_detector_ewma_slope():
    rng = np.random.default_rng(3)
    watch = ModelWatch()
    for _ in range(100):
        v = watch.observe(_qvec(rng), 128.0, 100.0)
    assert v["level"] == "ok"
    mse = 100.0
    seen = []
    for _ in range(60):
        mse *= 1.15  # exploding loss, stationary moments
        v = watch.observe(_qvec(rng), 128.0, mse)
        seen.append(v["level"])
    assert "alert" in seen  # the trend crossed TREND_ALERT
    assert v["loss_trend"] >= modelwatch_mod.TREND_ALERT


def test_nonfinite_quality_is_immediate_alert():
    rng = np.random.default_rng(5)
    watch = ModelWatch()
    q = _qvec(rng)
    q[QUALITY_INDEX["weight_norm"]] = math.nan
    v = watch.observe(q, 128.0, 100.0)
    assert v["level"] == "alert"
    assert v["alert_run"] == 1
    v = watch.observe(q, 128.0, 100.0)
    assert v["alert_run"] == 2
    # recovery: finite quality drops back to ok and resets the run
    v = watch.observe(_qvec(rng), 128.0, 100.0)
    assert v["level"] == "ok" and v["alert_run"] == 0


def test_per_tenant_tracks_and_worst_tenant_wins():
    rng = np.random.default_rng(9)
    watch = ModelWatch()
    for _ in range(150):
        q = np.stack([_qvec(rng), _qvec(rng, label_mean=50.0)])
        v = watch.observe(q, np.array([64.0, 64.0]), np.array([100.0, 90.0]))
        assert v["level"] == "ok"
    # only tenant 1 shifts: the model-level verdict follows the worst track
    for _ in range(modelwatch_mod.RECENT_WINDOW + 2):
        q = np.stack([_qvec(rng), _qvec(rng, label_mean=70.0)])
        v = watch.observe(q, np.array([64.0, 64.0]), np.array([100.0, 90.0]))
    assert v["level"] == "alert"
    view = watch.view()
    assert [t["level"] for t in view["tenants"]] == ["ok", "alert"]
    reg = _metrics.get_registry()
    assert reg.gauge("tenant.1.health_level").snapshot() == 2
    assert reg.gauge("tenant.0.health_level").snapshot() == 0


def test_view_and_checkpoint_snapshot_shapes():
    rng = np.random.default_rng(1)
    assert modelwatch_mod.last_model() is None
    assert modelwatch_mod.snapshot_for_checkpoint() is None
    for _ in range(4):
        modelwatch_mod.record_tick(_qvec(rng), 128.0, 50.0)
    view = modelwatch_mod.last_model()
    assert view["level"] == "ok"
    assert len(view["mse"]) == 4 and view["ticks"] == 4
    assert view["tenants"] == []  # single model: no per-tenant rows
    snap = modelwatch_mod.snapshot_for_checkpoint()
    assert snap["level"] == "ok" and snap["ticks"] == 4
    json.dumps(snap)  # json-safe (checkpoint meta + bundles carry it)


# ---------------------------------------------------------------------------
# the sentinel early-warning hook (forced verified-checkpoint save)


class _FakeCkpt:
    def __init__(self):
        self.saves = 0

    def save_now(self, totals):
        self.saves += 1
        return True


def test_sustained_alert_forces_one_checkpoint_per_episode():
    from twtml_tpu.apps.common import ModelWatchGuard
    from twtml_tpu.telemetry import blackbox as blackbox_mod

    rec = blackbox_mod.install(config={"t": 1})
    try:
        conf = ConfArguments().parse(["--modelWatchWindow", "3"])
        ckpt = _FakeCkpt()
        guard = ModelWatchGuard(conf, ckpt, {"count": 0, "batches": 0})
        rng = np.random.default_rng(2)
        bad = _qvec(rng)
        bad[QUALITY_INDEX["grad_norm"]] = math.inf  # nonfinite → alert
        out_bad = StepOutput(
            predictions=None, count=np.float32(64), mse=np.float32(1.0),
            real_stdev=np.float32(1.0), pred_stdev=np.float32(1.0),
            quality=bad,
        )
        for _ in range(2):
            guard.observe(out_bad)
        assert ckpt.saves == 0  # window (3) not reached yet
        guard.observe(out_bad)
        assert ckpt.saves == 1  # forced save at the window
        for _ in range(5):
            guard.observe(out_bad)
        assert ckpt.saves == 1  # ONE save per episode, not per batch
        good = StepOutput(
            predictions=None, count=np.float32(64), mse=np.float32(1.0),
            real_stdev=np.float32(1.0), pred_stdev=np.float32(1.0),
            quality=_qvec(rng),
        )
        guard.observe(good)  # episode closes
        for _ in range(3):
            guard.observe(out_bad)
        assert ckpt.saves == 2  # a NEW episode earns a new save
        reg = _metrics.get_registry()
        assert reg.counter("model.alert_checkpoints").snapshot() == 2
        kinds = [e["kind"] for e in rec.bundle("t")["events"]]
        assert kinds.count("modelwatch_alert_checkpoint") == 2
    finally:
        blackbox_mod.uninstall()


def test_guard_disabled_and_missing_quality_are_noops():
    from twtml_tpu.apps.common import ModelWatchGuard

    conf_off = ConfArguments().parse(["--modelWatch", "off"])
    guard = ModelWatchGuard(conf_off, _FakeCkpt(), {"batches": 0})
    assert not guard.enabled
    out = StepOutput(
        predictions=None, count=np.float32(4), mse=np.float32(1.0),
        real_stdev=np.float32(1.0), pred_stdev=np.float32(1.0),
    )
    guard.observe(out)  # must not raise
    guard_on = ModelWatchGuard(
        ConfArguments(), _FakeCkpt(), {"batches": 0}
    )
    guard_on.observe(out)  # quality=None → no-op
    assert modelwatch_mod.last_model() is None


# ---------------------------------------------------------------------------
# THE acceptance constraint: zero added fetches / zero added collectives
# with --modelWatch on, counted over real runs (the PR 1/5 law)


def test_modelwatch_adds_no_fetches_and_no_collectives(monkeypatch):
    import jax
    from jax.experimental import multihost_utils

    from twtml_tpu.apps.common import FetchPipeline, ModelWatchGuard
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.streaming.context import StreamingContext

    jax.devices()  # lock the conftest backend
    calls = {"allgather": 0, "get": 0}
    real_ag = multihost_utils.process_allgather

    def counting_ag(arr):
        calls["allgather"] += 1
        return real_ag(arr)

    monkeypatch.setattr(multihost_utils, "process_allgather", counting_ag)
    real_get = jax.device_get

    def counting_get(x):
        calls["get"] += 1
        return real_get(x)

    monkeypatch.setattr(jax, "device_get", counting_get)

    ssc = StreamingContext(batch_interval=0)
    stream = ssc.source_stream(
        SyntheticSource(total=64, seed=7, base_ms=NOW_MS),
        Featurizer(now_ms=NOW_MS),
        row_bucket=16, token_bucket=64, device_hash=True,
    )
    model = StreamingLinearRegressionWithSGD(num_iterations=2, quality=True)
    guard = ModelWatchGuard(
        ConfArguments(), None, {"count": 0, "batches": 0}
    )

    def handle(out, b, t, at_boundary=True):
        guard.observe(out, at_boundary=at_boundary)

    pipe = FetchPipeline(model, handle, deterministic=True)
    stream.foreach_batch(pipe.on_batch)
    ssc.start(lockstep=True)
    assert ssc.await_termination(timeout=120)
    ssc.stop()
    pipe.flush()
    assert not ssc.failed
    assert ssc.batches_processed >= 4

    reg = _metrics.get_registry().snapshot()
    ticks = reg["counters"]["lockstep.ticks"]
    # ZERO added collectives: still exactly ONE allgather per lockstep tick
    assert calls["allgather"] == ticks
    # ZERO added host fetches: one per dispatched batch — the quality leaf
    # rides the StepOutput transfer, the watcher never touches the device
    assert calls["get"] == ssc.batches_processed
    view = modelwatch_mod.last_model()
    assert view is not None and view["ticks"] == ssc.batches_processed


CLOSED = "http://127.0.0.1:9"
BASE = [
    "--source", "replay", "--seconds", "0", "--backend", "cpu",
    "--batchBucket", "16", "--tokenBucket", "64", "--master", "local[1]",
    "--lightning", CLOSED, "--twtweb", CLOSED, "--webTimeout", "0.2",
]


def _corpus_file(tmp_path, total=8 * 16, seed=51):
    from tools.bench_suite import _status_json

    path = tmp_path / "tweets.jsonl"
    with open(path, "w") as fh:
        for s in SyntheticSource(
            total=total, seed=seed, base_ms=NOW_MS
        ).produce():
            fh.write(json.dumps(_status_json(s)) + "\n")
    return path


def _run_counting_fetches(conf_args):
    import jax

    from twtml_tpu.apps import linear_regression as app

    jax.devices()
    calls = {"n": 0}
    real = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real(x)

    jax.device_get = counting
    try:
        totals = app.run(ConfArguments().parse(list(conf_args)))
    finally:
        jax.device_get = real
    return totals, calls["n"]


def test_app_default_modelwatch_one_fetch_per_tick(tmp_path, monkeypatch):
    """ACCEPTANCE: a real app run with the DEFAULT --modelWatch on fetches
    exactly once per dispatched batch, the watcher records every tick, and
    the checkpoint meta carries the quality stamp."""
    from twtml_tpu.checkpoint import Checkpointer

    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    path = _corpus_file(tmp_path)
    totals, fetches = _run_counting_fetches(
        BASE + ["--replayFile", str(path),
                "--checkpointDir", str(tmp_path / "ck"),
                "--checkpointEvery", "1"]
    )
    assert totals["batches"] == 8
    assert fetches == 8  # ONE device_get per tick, quality riding along
    view = modelwatch_mod.last_model()
    assert view is not None and view["ticks"] == 8
    assert view["level"] == "ok"  # short healthy stream: no verdict drama
    reg = _metrics.get_registry().snapshot()
    assert reg["gauges"]["model.weight_norm"] > 0
    # checkpoint quality-stamp roundtrip (ACCEPTANCE)
    _, meta = Checkpointer(str(tmp_path / "ck")).restore()
    assert meta["quality"]["level"] == "ok"
    assert meta["quality"]["ticks"] >= 1
    assert meta["quality"]["weight_norm"] > 0
    # tools/model_report renders the history (exit 0) and --json parses
    assert model_report.main([str(tmp_path / "ck")]) == 0
    assert model_report.main([str(tmp_path / "ck"), "--json"]) == 0


def test_app_modelwatch_off_records_nothing(tmp_path, monkeypatch):
    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    path = _corpus_file(tmp_path)
    totals, fetches = _run_counting_fetches(
        BASE + ["--replayFile", str(path), "--modelWatch", "off"]
    )
    assert totals["batches"] == 8
    assert fetches == 8
    assert modelwatch_mod.last_model() is None


def test_app_m4_per_tenant_quality_rides_one_fetch(tmp_path, monkeypatch):
    """The tenant plane's [M, Q] quality leaf rides the ONE stacked fetch:
    per-tenant drift tracks materialize with the fetch count unchanged."""
    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    path = _corpus_file(tmp_path)
    totals, fetches = _run_counting_fetches(
        BASE + ["--replayFile", str(path), "--tenants", "4"]
    )
    assert totals["batches"] == 8 and totals["tenants"] == 4
    assert fetches == 8  # ONE device_get per tick, M=4 and quality riding
    view = modelwatch_mod.last_model()
    assert view is not None and len(view["tenants"]) == 4
    reg = _metrics.get_registry().snapshot()
    assert "tenant.0.health_level" in reg["gauges"]


# ---------------------------------------------------------------------------
# tools/model_report.py exit codes (the CHECK contract)


def test_model_report_malformed_exits_2(tmp_path):
    assert model_report.main([str(tmp_path / "absent")]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert model_report.main([str(empty)]) == 2
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "ckpt-000000000001.npz").write_text("not an archive")
    assert model_report.main([str(bad)]) == 2
    assert model_report.main([]) == 2


def test_model_report_renders_unstamped_and_quarantined(tmp_path):
    from twtml_tpu.checkpoint import Checkpointer

    ck = Checkpointer(str(tmp_path))
    ck.save(1, np.zeros(8, np.float32), {"count": 16})  # no quality stamp
    ck.save(2, np.full(8, np.nan, np.float32), {"count": 32})  # quarantined
    rows = model_report.load_history(str(tmp_path))
    assert [r["step"] for r in rows] == [1, 2]
    assert rows[0]["quality"] is None and not rows[0]["quarantined"]
    assert rows[1]["quarantined"] and not rows[1]["finite"]
    text = model_report.render(rows)
    assert "(unstamped)" in text and "QUARANTINED" in text
    assert model_report.main([str(tmp_path)]) == 0


# ---------------------------------------------------------------------------
# the ModelHealth wire type + /api/model


def test_model_health_wire_roundtrip():
    from twtml_tpu.telemetry.api_types import ModelHealth, decode, encode

    msg = ModelHealth(
        level="warn", driftScore=5.2, lossTrend=0.31, weightNorm=120.5,
        updateNorm=3.25, gradNorm=4000.0, mse=[10.0, 11.0],
        tenants=[{"tenant": 0, "level": "warn", "drift": 5.2}], episodes=2,
    )
    wire = encode(msg)
    assert json.loads(wire)["jsonClass"] == "ModelHealth"
    assert decode(wire) == msg


def test_api_model_endpoint_and_cache_dispatch(tmp_path):
    from twtml_tpu.telemetry.api_types import ModelHealth
    from twtml_tpu.telemetry.web_client import WebClient
    from twtml_tpu.web.cache import ApiCache
    from twtml_tpu.web.server import Server

    cache = ApiCache(backup_file=str(tmp_path / "twtml-web.json"))
    srv = Server(port=0, host="127.0.0.1", cache=cache)
    srv.start_background()
    try:
        port = srv._runner.addresses[0][1]
        url = f"http://127.0.0.1:{port}"
        client = WebClient(url)
        # default before any post: a well-formed empty ModelHealth
        import urllib.request

        with urllib.request.urlopen(url + "/api/model", timeout=2) as resp:
            doc = json.loads(resp.read())
        assert doc["jsonClass"] == "ModelHealth" and doc["level"] == "ok"
        client.model_health(
            level="alert", drift_score=9.5, loss_trend=1.4,
            weight_norm=100.0, update_norm=2.0, grad_norm=500.0,
            mse=[5.0, 6.0, 7.0],
            tenants=[{"tenant": 1, "level": "alert", "drift": 9.5}],
            episodes=3,
        )
        with urllib.request.urlopen(url + "/api/model", timeout=2) as resp:
            doc = json.loads(resp.read())
        assert doc["level"] == "alert"
        assert doc["driftScore"] == 9.5
        assert doc["mse"] == [5.0, 6.0, 7.0]
        assert doc["tenants"][0]["tenant"] == 1
        assert doc["episodes"] == 3
        assert isinstance(cache._model, ModelHealth)
    finally:
        srv.stop()


def test_session_stats_publishes_model_health_and_host_gauges(monkeypatch):
    """publish_metrics ships the modelwatch view as a ModelHealth message
    and samples the host gauges (RSS + uptime) each publish tick."""
    from twtml_tpu.telemetry.session_stats import SessionStats

    sent = []

    class _Conf:
        lightning = CLOSED
        twtweb = CLOSED
        webTimeout = 0.2

    session = SessionStats(_Conf())
    monkeypatch.setattr(
        session.web, "model_health", lambda **kw: sent.append(kw)
    )
    monkeypatch.setattr(session.web, "metrics", lambda *a, **k: None)
    rng = np.random.default_rng(4)
    modelwatch_mod.record_tick(_qvec(rng), 128.0, 42.0)
    session.publish_metrics()
    assert len(sent) == 1
    assert sent[0]["level"] == "ok" and sent[0]["mse"] == [42.0]
    reg = _metrics.get_registry().snapshot()
    assert reg["gauges"]["host.rss_mb"] > 0
    assert reg["gauges"]["host.uptime_s"] >= 0


# ---------------------------------------------------------------------------
# conf flags


def test_conf_flags():
    conf = ConfArguments()
    assert conf.modelWatch == "on" and conf.modelWatchWindow == 8
    conf = ConfArguments().parse(
        ["--modelWatch", "off", "--modelWatchWindow", "16"]
    )
    assert conf.modelWatch == "off" and conf.modelWatchWindow == 16
    with pytest.raises(SystemExit):
        ConfArguments().parse(["--modelWatch", "bogus"])
