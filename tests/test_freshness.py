"""End-to-end freshness plane (ISSUE 16): event-time watermarks, per-batch
critical-path lineage, and staleness SLOs at zero added fetches.

The laws under test, in the order the ISSUE states them:
- **lag/watermark exactness** under the pinned ``TWTML_NOW_MS`` seam: the
  event→delivery lag is exactly ``delivered − max(created_at_ms)`` and the
  low watermark exactly ``delivered − oldest event-time still in flight``;
- **critical-path attribution**: a seeded stage-clock delta between open
  and delivery names that edge and ticks its counter;
- **zero added fetches / zero added collectives** with the plane ON —
  asserted by COUNTING ``jax.device_get`` / ``process_allgather`` over a
  real lockstep run and a real app run (the PR 1/5/8 idiom);
- **off bit-parity**: ``--freshness off`` never touches the lineage FIFOs
  and the app's weights are bit-identical to the ON run's (the plane is a
  pure host-side observer);
- **SLO gate**: a sustained ``--freshnessSloMs`` breach fires ONE blackbox
  event + ONE forced verified-checkpoint save per episode (warn-only);
- **serving staleness**: ``serving.snapshot_age_s`` through the clock seam,
  ``model_staleness_s`` in every predict response, and the warn-only
  ``--servingStaleSloS`` breach episode;
- the ``Freshness`` wire type, ``/api/freshness``, the sideband columns,
  ``tools/freshness_report.py`` exit codes, and the satellite gauges
  (``ingest.event_time_lag_ms``, ``host.rss_slope_mb_per_min``).
"""

import json
import os
import sys
import types

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools import freshness_report  # noqa: E402
from twtml_tpu.config import ConfArguments  # noqa: E402
from twtml_tpu.features.featurizer import Featurizer  # noqa: E402
from twtml_tpu.models import (  # noqa: E402
    StreamingLinearRegressionWithSGD,
)
from twtml_tpu.streaming.sources import (  # noqa: E402
    SyntheticSource,
    _record_event_lag,
)
from twtml_tpu.telemetry import blackbox as blackbox_mod  # noqa: E402
from twtml_tpu.telemetry import freshness as _freshness  # noqa: E402
from twtml_tpu.telemetry import lineage as _lineage  # noqa: E402
from twtml_tpu.telemetry import metrics as _metrics  # noqa: E402
from twtml_tpu.telemetry import sideband as _sideband  # noqa: E402

NOW_MS = 1785320000000
CLOSED = "http://127.0.0.1:9"


@pytest.fixture(autouse=True)
def _fresh_state():
    _metrics.reset_for_tests()
    _freshness.reset_for_tests()  # also clears the lineage FIFOs
    _sideband.reset_for_tests()
    yield
    _metrics.reset_for_tests()
    _freshness.reset_for_tests()
    _sideband.reset_for_tests()


def _st(created_at_ms):
    """A minimal status-like object for the lineage event-span reader."""
    return types.SimpleNamespace(created_at_ms=created_at_ms)


def _deliver(statuses):
    """One full open → dispatch → delivery cycle through the plane."""
    _lineage.open_batch(statuses)
    _lineage.mark_dispatch()
    return _freshness.record_delivery()


# ---------------------------------------------------------------------------
# watermark / lag exactness under the pinned clock seam


def test_lag_and_watermark_exactness(monkeypatch):
    """ACCEPTANCE: with TWTML_NOW_MS pinned, the event→delivery lag and the
    low watermark are EXACT ms values, not approximations."""
    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    _freshness.configure(on=True)
    verdict = _deliver([_st(NOW_MS - 4000), _st(NOW_MS - 1000)])
    # lag is measured to the NEWEST event in the batch; with the FIFOs
    # drained the watermark falls back to the batch's own OLDEST event
    assert verdict["event_lag_ms"] == 1000.0
    assert verdict["watermark_lag_ms"] == 4000.0
    assert not verdict["breach"]  # no SLO armed
    view = _freshness.last_freshness()
    assert view["batches"] == 1 and view["rows"] == 2
    assert view["eventLagMs"] == 1000.0
    assert view["eventLagP50Ms"] == 1000.0
    assert view["eventLagP95Ms"] == 1000.0
    assert view["eventLagP99Ms"] == 1000.0
    assert view["watermarkLagMs"] == 4000.0
    assert view["watermark"] == [4000.0]
    reg = _metrics.get_registry()
    assert reg.gauge("freshness.event_lag_p95_ms").snapshot() == 1000.0
    assert reg.gauge("freshness.watermark_lag_ms").snapshot() == 4000.0
    snap = reg.snapshot()
    assert snap["histograms"]["freshness.event_lag_ms"]["count"] == 1


def test_watermark_tracks_oldest_inflight_event(monkeypatch):
    """The low watermark is ``delivered − min(event_min over BOTH FIFOs)``:
    a still-in-flight older batch holds the watermark down."""
    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    _freshness.configure(on=True)
    _lineage.open_batch([_st(NOW_MS - 9000), _st(NOW_MS - 3000)])  # A
    _lineage.open_batch([_st(NOW_MS - 2000)])                       # B
    _lineage.mark_dispatch(2)
    v_a = _freshness.record_delivery()
    # A delivered while B (oldest event NOW-2000) is still in flight
    assert v_a["event_lag_ms"] == 3000.0
    assert v_a["watermark_lag_ms"] == 2000.0
    v_b = _freshness.record_delivery()
    assert v_b["event_lag_ms"] == 2000.0
    assert v_b["watermark_lag_ms"] == 2000.0  # own-batch fallback
    assert _lineage.depths() == (0, 0)


def test_publish_lag_drained_at_stats_tick(monkeypatch):
    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    _freshness.configure(on=True)
    _deliver([_st(NOW_MS - 1500)])
    view = _freshness.last_freshness()
    assert view["publishLagP95Ms"] == -1.0  # nothing published yet
    _freshness.record_publish()  # the SessionStats._update hook
    view = _freshness.last_freshness()
    assert view["publishLagP95Ms"] == 1500.0
    assert _metrics.get_registry().gauge(
        "freshness.publish_lag_p95_ms"
    ).snapshot() == 1500.0


def test_unknown_event_times_fold_to_no_lag(monkeypatch):
    """Statuses without created_at_ms (the synthetic wrapper default) still
    count the batch but record no lag — the percentile windows only carry
    known event times."""
    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    _freshness.configure(on=True)
    verdict = _deliver([_st(0), _st(0)])
    assert verdict["event_lag_ms"] == -1.0
    view = _freshness.last_freshness()
    assert view["batches"] == 1 and view["eventLagP95Ms"] == -1.0
    assert _freshness.last_event_lag_ms() == 0.0  # the sideband column


# ---------------------------------------------------------------------------
# critical-path attribution on seeded stage deltas


def test_critical_path_attribution_on_seeded_stage_delays(monkeypatch):
    """ACCEPTANCE: the dominant seam-to-seam stage delta between open and
    delivery names the critical edge and ticks its counter."""
    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    _freshness.configure(on=True)
    _lineage.open_batch([_st(NOW_MS - 100)])
    _sideband.record_stage("dispatch", 0.5)  # 500 ms on the dispatch edge
    _lineage.mark_dispatch()
    verdict = _freshness.record_delivery()
    assert verdict["critical"] == "dispatch"
    reg = _metrics.get_registry()
    assert reg.counter("freshness.critical.dispatch.ticks").snapshot() == 1
    # second batch: featurize dominates (dispatch clock unchanged since its
    # open snapshot, so its delta is 0 for this batch)
    _lineage.open_batch([_st(NOW_MS - 100)])
    _sideband.record_stage("featurize", 2.0)
    _lineage.mark_dispatch()
    verdict = _freshness.record_delivery()
    assert verdict["critical"] == "featurize"
    view = _freshness.last_freshness()
    assert view["critical"] == "featurize"
    assert view["criticalTicks"] == {"dispatch": 1, "featurize": 1}
    assert reg.counter("freshness.critical.featurize.ticks").snapshot() == 1


def test_quiet_pipeline_has_no_critical_edge(monkeypatch):
    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    _freshness.configure(on=True)
    verdict = _deliver([_st(NOW_MS - 100)])  # no stage work recorded
    assert verdict["critical"] == ""
    assert _freshness.last_freshness()["criticalTicks"] == {}


# ---------------------------------------------------------------------------
# lineage FIFO discipline: off is a no-op, blanks keep alignment


def test_off_plane_never_touches_the_fifos():
    """--freshness off bit-parity precondition: every lineage entry point
    is a no-op, so the off arm IS the pre-plane hot path."""
    assert not _lineage.enabled()
    _lineage.open_batch([_st(NOW_MS)])
    _lineage.mark_dispatch()
    assert _lineage.depths() == (0, 0)
    assert _lineage.pop_delivery() is None
    assert _lineage.open_event_floor() == 0
    assert _freshness.record_delivery() is None
    assert _freshness.last_freshness() is None
    assert _freshness.snapshot_for_checkpoint() is None
    assert _freshness.last_event_lag_ms() == 0.0


def test_blank_dispatches_keep_the_fifos_aligned(monkeypatch):
    """Dispatches with no matching open (serving, warmup, bare pipelines)
    push blanks; sheds drop the newest open record."""
    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    _freshness.configure(on=True)
    _lineage.mark_dispatch()  # no open record: a blank
    assert _lineage.depths() == (0, 1)
    assert _freshness.record_delivery() is None  # blank pops silently
    _lineage.open_batch([_st(NOW_MS - 100)])
    _lineage.drop_newest()  # skip_empty shed before dispatch
    assert _lineage.depths() == (0, 0)
    # a real batch after the churn still matches positionally
    verdict = _deliver([_st(NOW_MS - 700)])
    assert verdict["event_lag_ms"] == 700.0
    assert _freshness.last_freshness()["batches"] == 1


# ---------------------------------------------------------------------------
# THE acceptance constraint: zero added fetches / zero added collectives
# with the plane ON, counted over a real lockstep run (the PR 1/5/8 law)


def test_freshness_adds_no_fetches_and_no_collectives(monkeypatch):
    import jax
    from jax.experimental import multihost_utils

    from twtml_tpu.apps.common import FetchPipeline, FreshnessGuard
    from twtml_tpu.streaming.context import StreamingContext

    jax.devices()  # lock the conftest backend
    calls = {"allgather": 0, "get": 0}
    real_ag = multihost_utils.process_allgather

    def counting_ag(arr):
        calls["allgather"] += 1
        return real_ag(arr)

    monkeypatch.setattr(multihost_utils, "process_allgather", counting_ag)
    real_get = jax.device_get

    def counting_get(x):
        calls["get"] += 1
        return real_get(x)

    monkeypatch.setattr(jax, "device_get", counting_get)

    _freshness.configure(on=True)
    ssc = StreamingContext(batch_interval=0)
    stream = ssc.source_stream(
        SyntheticSource(total=64, seed=7, base_ms=NOW_MS),
        Featurizer(now_ms=NOW_MS),
        row_bucket=16, token_bucket=64, device_hash=True,
    )
    model = StreamingLinearRegressionWithSGD(num_iterations=2)
    guard = FreshnessGuard(ConfArguments(), None, {"count": 0, "batches": 0})

    def handle(out, b, t, at_boundary=True):
        guard.observe(out, at_boundary=at_boundary)

    pipe = FetchPipeline(model, handle, deterministic=True)
    stream.foreach_batch(pipe.on_batch)
    ssc.start(lockstep=True)
    assert ssc.await_termination(timeout=120)
    ssc.stop()
    pipe.flush()
    assert not ssc.failed
    assert ssc.batches_processed >= 4

    reg = _metrics.get_registry().snapshot()
    ticks = reg["counters"]["lockstep.ticks"]
    # ZERO added collectives: still exactly ONE allgather per lockstep tick
    assert calls["allgather"] == ticks
    # ZERO added host fetches: one per dispatched batch — the lineage
    # records are pure host-side stamps, the plane never touches the device
    assert calls["get"] == ssc.batches_processed
    view = _freshness.last_freshness()
    assert view is not None and view["batches"] == ssc.batches_processed
    assert _lineage.depths() == (0, 0)  # every record matched a delivery


# ---------------------------------------------------------------------------
# app-level acceptance: counting + checkpoint stamp + OFF bit-parity


BASE = [
    "--source", "replay", "--seconds", "0", "--backend", "cpu",
    "--batchBucket", "16", "--tokenBucket", "64", "--master", "local[1]",
    "--lightning", CLOSED, "--twtweb", CLOSED, "--webTimeout", "0.2",
]


def _corpus_file(tmp_path, total=8 * 16, seed=51):
    from tools.bench_suite import _status_json

    statuses = list(
        SyntheticSource(total=total, seed=seed, base_ms=NOW_MS).produce()
    )
    # the synthetic wrapper carries created_at_ms=0: stamp known event
    # times so the replayed stream exercises the lag-fold path exactly
    for j, s in enumerate(statuses):
        s.created_at_ms = NOW_MS - 1000 * (j + 1)
    path = tmp_path / "tweets.jsonl"
    with open(path, "w") as fh:
        for s in statuses:
            fh.write(json.dumps(_status_json(s)) + "\n")
    return path


def _run_counting_fetches(conf_args):
    import jax

    from twtml_tpu.apps import linear_regression as app

    jax.devices()
    calls = {"n": 0}
    real = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real(x)

    jax.device_get = counting
    try:
        totals = app.run(ConfArguments().parse(list(conf_args)))
    finally:
        jax.device_get = real
    return totals, calls["n"]


def test_app_default_freshness_counts_and_off_is_bit_exact(
    tmp_path, monkeypatch
):
    """ACCEPTANCE: a real app run with the DEFAULT --freshness on fetches
    exactly once per batch, the view and the checkpoint freshness stamp
    materialize, and a --freshness off run lands BIT-identical weights
    (the plane is observation-only)."""
    from twtml_tpu.checkpoint import Checkpointer

    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    path = _corpus_file(tmp_path)
    totals_on, fetches_on = _run_counting_fetches(
        BASE + ["--replayFile", str(path),
                "--checkpointDir", str(tmp_path / "ck_on"),
                "--checkpointEvery", "1"]
    )
    assert totals_on["batches"] == 8
    assert fetches_on == 8  # ONE device_get per batch, the plane adds none
    view = _freshness.last_freshness()
    assert view is not None and view["batches"] == 8
    assert view["eventLagMs"] > 0  # real event times flowed end to end
    assert view["eventLagP95Ms"] > 0
    assert len(view["watermark"]) >= 1
    reg = _metrics.get_registry().snapshot()
    assert reg["gauges"]["freshness.event_lag_p95_ms"] > 0
    assert reg["histograms"]["freshness.event_lag_ms"]["count"] == 8
    # checkpoint freshness-stamp roundtrip (ACCEPTANCE)
    w_on, meta = Checkpointer(str(tmp_path / "ck_on")).restore()
    assert meta["freshness"]["batches"] >= 1
    assert meta["freshness"]["event_lag_p95_ms"] > 0
    json.dumps(meta["freshness"])  # json-safe

    totals_off, fetches_off = _run_counting_fetches(
        BASE + ["--replayFile", str(path), "--freshness", "off",
                "--checkpointDir", str(tmp_path / "ck_off"),
                "--checkpointEvery", "1"]
    )
    assert totals_off["batches"] == 8
    assert fetches_off == 8
    assert _freshness.last_freshness() is None  # plane fully off
    assert _lineage.depths() == (0, 0)
    w_off, meta_off = Checkpointer(str(tmp_path / "ck_off")).restore()
    assert "freshness" not in meta_off
    # the bit-parity law: identical weights with the plane on or off
    assert np.asarray(w_on).tobytes() == np.asarray(w_off).tobytes()
    assert totals_on["count"] == totals_off["count"]


# ---------------------------------------------------------------------------
# the SLO gate: blackbox events + ONE forced checkpoint per episode


class _FakeCkpt:
    def __init__(self):
        self.saves = 0

    def save_now(self, totals):
        self.saves += 1
        return True


def test_sustained_slo_breach_forces_one_checkpoint_per_episode(monkeypatch):
    from twtml_tpu.apps.common import FreshnessGuard

    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    rec = blackbox_mod.install(config={"t": 1})
    try:
        _freshness.configure(on=True, slo_ms=100.0, window=3)
        ckpt = _FakeCkpt()
        guard = FreshnessGuard(ConfArguments(), ckpt, {"batches": 0})
        breach = [_st(NOW_MS - 500)]  # lag 500 ms > SLO 100 ms
        ok = [_st(NOW_MS - 50)]       # lag 50 ms, under SLO

        for _ in range(2):
            _lineage.open_batch(breach)
            _lineage.mark_dispatch()
            guard.observe(None)
        assert ckpt.saves == 0  # window (3) not reached yet
        # the episode fires on the 3rd breach, but weights are mid-flight
        # (at_boundary=False): the save waits for a weights-current delivery
        _lineage.open_batch(breach)
        _lineage.mark_dispatch()
        guard.observe(None, at_boundary=False)
        assert ckpt.saves == 0
        reg = _metrics.get_registry()
        assert reg.counter("freshness.slo_breaches").snapshot() == 1
        _lineage.open_batch(breach)
        _lineage.mark_dispatch()
        guard.observe(None)
        assert ckpt.saves == 1  # forced save at the first boundary
        for _ in range(5):
            _lineage.open_batch(breach)
            _lineage.mark_dispatch()
            guard.observe(None)
        assert ckpt.saves == 1  # ONE save per episode, not per batch
        _lineage.open_batch(ok)
        _lineage.mark_dispatch()
        guard.observe(None)  # episode closes
        for _ in range(3):
            _lineage.open_batch(breach)
            _lineage.mark_dispatch()
            guard.observe(None)
        assert ckpt.saves == 2  # a NEW episode earns a new save
        assert reg.counter("freshness.slo_breaches").snapshot() == 2
        assert reg.counter("freshness.slo_checkpoints").snapshot() == 2
        kinds = [e["kind"] for e in rec.bundle("t")["events"]]
        assert kinds.count("freshness_slo_breach") == 2
        view = _freshness.last_freshness()
        assert view["breaches"] == 2 and view["sloMs"] == 100.0
    finally:
        blackbox_mod.uninstall()


def test_guard_disabled_is_a_noop():
    from twtml_tpu.apps.common import FreshnessGuard

    conf_off = ConfArguments().parse(["--freshness", "off"])
    guard = FreshnessGuard(conf_off, _FakeCkpt(), {"batches": 0})
    assert not guard.enabled
    guard.observe(None)  # must not raise
    assert _freshness.last_freshness() is None


# ---------------------------------------------------------------------------
# serving staleness: snapshot age through the clock seam + per-response
# model staleness + the --servingStaleSloS breach episode


def test_serving_snapshot_age_staleness_and_breach_episode(monkeypatch):
    from twtml_tpu.serving import ServingSnapshot
    from twtml_tpu.serving.plane import ServingPlane

    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    rec = blackbox_mod.install(config={"t": 1})
    plane = None
    try:
        snap = ServingSnapshot(step=3, weights=np.zeros(1004, np.float32))
        plane = ServingPlane(
            snap, featurizer=Featurizer(now_ms=NOW_MS), batch_rows=32,
            max_wait_ms=5.0, depth=4, stale_slo_s=5.0,
        )
        plane.start()
        statuses = list(SyntheticSource(total=8, seed=3).produce())
        res = plane.submit(statuses).result(timeout=120)
        # dispatch-time model staleness in EVERY predict response; the
        # pinned clock makes it exactly 0 (installed and dispatched at the
        # same pinned instant)
        assert res["model_staleness_s"] == 0.0
        assert res["snapshot_step"] == 3
        view = plane.stats()
        assert view["snapshotAgeS"] == 0.0
        reg = _metrics.get_registry()
        assert reg.gauge("serving.snapshot_age_s").snapshot() == 0.0
        assert reg.counter("serve.stale_breaches").snapshot() == 0
        # advance the pinned clock past the SLO: ONE breach episode
        monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS + 10_000))
        view = plane.stats()
        assert view["snapshotAgeS"] == 10.0
        assert reg.counter("serve.stale_breaches").snapshot() == 1
        plane.stats()  # still the same episode: no second count
        assert reg.counter("serve.stale_breaches").snapshot() == 1
        # a fresh install (clock back under the SLO) closes the episode...
        monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS + 1_000))
        plane.stats()
        # ...and a NEW sustained breach opens a new one
        monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS + 20_000))
        view = plane.stats()
        assert view["snapshotAgeS"] == 20.0
        assert reg.counter("serve.stale_breaches").snapshot() == 2
        kinds = [e["kind"] for e in rec.bundle("t")["events"]]
        assert kinds.count("serving_stale_breach") == 2
    finally:
        if plane is not None:
            plane.stop()
        blackbox_mod.uninstall()


# ---------------------------------------------------------------------------
# the sideband columns: the watermark rides the EXISTING cadence allgather


def test_sideband_carries_wire_pack_and_event_lag_columns(monkeypatch):
    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    assert "wire_pack_ms" in _sideband.FIELDS
    assert "event_lag_ms" in _sideband.FIELDS
    assert _sideband.STAGE_FIELDS["wire_pack_ms"] == "wire_pack"
    collector = _sideband.SidebandCollector()
    _freshness.configure(on=True)
    _sideband.record_stage("wire_pack", 0.25)
    _deliver([_st(NOW_MS - 1234)])
    vec = collector.collect()
    assert vec[_sideband.FIELDS.index("wire_pack_ms")] == 250.0
    assert vec[_sideband.FIELDS.index("event_lag_ms")] == 1234.0
    # the column is a plain registry read: a second collect with no new
    # delivery repeats the last value, never blocks, never fetches
    assert vec.shape == (_sideband.WIDTH,)


# ---------------------------------------------------------------------------
# SessionStats publishes the Freshness view + the rolling RSS slope


def test_session_stats_publishes_freshness_and_rss_slope(monkeypatch):
    from twtml_tpu.telemetry.session_stats import SessionStats

    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    sent = []

    class _Conf:
        lightning = CLOSED
        twtweb = CLOSED
        webTimeout = 0.2

    session = SessionStats(_Conf())
    monkeypatch.setattr(session.web, "freshness", lambda v: sent.append(v))
    monkeypatch.setattr(session.web, "metrics", lambda *a, **k: None)
    session.publish_metrics()
    assert sent == []  # nothing delivered yet: no Freshness frame
    _freshness.configure(on=True)
    _deliver([_st(NOW_MS - 900)])
    session.publish_metrics()
    assert len(sent) == 1
    assert sent[0]["batches"] == 1 and sent[0]["eventLagMs"] == 900.0
    reg = _metrics.get_registry().snapshot()
    # the continuous soak estimator (ISSUE 16 satellite): present every
    # publish tick; ~0 over two instant samples
    assert "host.rss_slope_mb_per_min" in reg["gauges"]


def test_rss_slope_least_squares():
    from twtml_tpu.utils.rss import slope_mb_per_min

    # 10 MB/min of linear growth, sampled every 30 s
    samples = [(30.0 * k, 100.0 + 5.0 * k) for k in range(8)]
    assert slope_mb_per_min(samples) == pytest.approx(10.0)
    assert slope_mb_per_min([]) == 0.0
    assert slope_mb_per_min([(0.0, 100.0)]) == 0.0
    assert slope_mb_per_min([(5.0, 100.0), (5.0, 200.0)]) == 0.0  # no var
    # the soak tool's estimator IS this function (one estimator, two faces)
    from tools.soak import _slope_mb_per_min

    assert _slope_mb_per_min(samples) == pytest.approx(10.0)


def test_ingest_event_time_lag_gauge(monkeypatch):
    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    _record_event_lag(NOW_MS - 2500)
    reg = _metrics.get_registry()
    assert reg.gauge("ingest.event_time_lag_ms").snapshot() == 2500.0
    _record_event_lag(0)  # unknown event time: gauge untouched
    assert reg.gauge("ingest.event_time_lag_ms").snapshot() == 2500.0


# ---------------------------------------------------------------------------
# the Freshness wire type + /api/freshness


def test_freshness_wire_roundtrip():
    from twtml_tpu.telemetry.api_types import Freshness, decode, encode

    msg = Freshness(
        batches=12, rows=640, eventLagMs=640.0, eventLagP50Ms=640.0,
        eventLagP95Ms=813.0, eventLagP99Ms=1500.0, publishLagP95Ms=990.0,
        watermarkLagMs=870.0, watermark=[900.0, 880.0, 870.0],
        critical="dispatch", criticalTicks={"dispatch": 9, "fetch": 3},
        sloMs=1000.0, breachRun=2, breaches=1,
    )
    wire = encode(msg)
    assert json.loads(wire)["jsonClass"] == "Freshness"
    assert decode(wire) == msg


def test_api_freshness_endpoint_and_cache_dispatch(tmp_path):
    import urllib.request

    from twtml_tpu.telemetry.api_types import Freshness
    from twtml_tpu.telemetry.web_client import WebClient
    from twtml_tpu.web.cache import ApiCache
    from twtml_tpu.web.server import Server

    cache = ApiCache(backup_file=str(tmp_path / "twtml-web.json"))
    srv = Server(port=0, host="127.0.0.1", cache=cache)
    srv.start_background()
    try:
        port = srv._runner.addresses[0][1]
        url = f"http://127.0.0.1:{port}"
        # default before any post: a well-formed empty Freshness
        with urllib.request.urlopen(url + "/api/freshness", timeout=2) as r:
            doc = json.loads(r.read())
        assert doc["jsonClass"] == "Freshness" and doc["batches"] == 0
        client = WebClient(url)
        view = {
            "batches": 5, "rows": 80, "eventLagMs": 700.0,
            "eventLagP95Ms": 813.0, "watermarkLagMs": 870.0,
            "watermark": [900.0, 870.0], "critical": "fetch",
            "criticalTicks": {"fetch": 5}, "breaches": 1,
            "not_a_field": "dropped",  # unknown keys must not break the post
        }
        client.freshness(view)
        with urllib.request.urlopen(url + "/api/freshness", timeout=2) as r:
            doc = json.loads(r.read())
        assert doc["batches"] == 5
        assert doc["eventLagP95Ms"] == 813.0
        assert doc["watermark"] == [900.0, 870.0]
        assert doc["critical"] == "fetch"
        assert doc["criticalTicks"] == {"fetch": 5}
        assert "not_a_field" not in doc
        assert isinstance(cache._freshness, Freshness)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# tools/freshness_report.py exit codes (the CHECK contract)


def test_freshness_report_malformed_exits_2(tmp_path):
    assert freshness_report.main([]) == 2
    assert freshness_report.main([str(tmp_path / "absent.json")]) == 2
    bad = tmp_path / "bad.json"
    bad.write_text("not json at all")
    assert freshness_report.main([str(bad)]) == 2
    wrong = tmp_path / "wrong.json"
    wrong.write_text(json.dumps({"kind": "something-else"}))
    assert freshness_report.main([str(wrong)]) == 2


def test_freshness_report_renders_a_real_bundle(tmp_path, capsys, monkeypatch):
    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    blackbox_mod.install(config={"t": 1})
    try:
        # window=1: the very first over-SLO delivery is a sustained episode
        _freshness.configure(on=True, slo_ms=100.0, window=1)
        _lineage.open_batch([_st(NOW_MS - 500)])
        _sideband.record_stage("fetch", 0.3)
        _lineage.mark_dispatch()
        verdict = _freshness.record_delivery()
        assert verdict["sustained"]
        path = blackbox_mod.dump(
            "freshness-test", out_dir=str(tmp_path), force=True
        )
        assert path is not None
    finally:
        blackbox_mod.uninstall()
    assert freshness_report.main([path]) == 0
    text = capsys.readouterr().out
    assert "p95 500 ms" in text
    assert "fetch" in text  # the critical edge table
    assert "1 breach episode(s)" in text
    assert freshness_report.main([path, "--json"]) == 0
    summary = json.loads(capsys.readouterr().out.strip())
    assert summary["event_lag_p95_ms"] == 500.0
    assert summary["critical_ticks"] == {"fetch": 1}
    assert summary["critical"] == "fetch"
    assert summary["slo_breaches"] == 1
    assert summary["event_lag_batches"] == 1
    assert [e["kind"] for e in summary["breach_events"]] == [
        "freshness_slo_breach"
    ]


def test_freshness_report_handles_plane_off_bundles(tmp_path, capsys):
    """A bundle from a run predating the plane (or --freshness off) is
    well-formed: exit 0 with the no-telemetry note, never exit 2."""
    blackbox_mod.install(config={"t": 1})
    try:
        path = blackbox_mod.dump("quiet", out_dir=str(tmp_path), force=True)
    finally:
        blackbox_mod.uninstall()
    assert freshness_report.main([path]) == 0
    assert "no freshness telemetry" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# conf flags


def test_conf_flags():
    conf = ConfArguments()
    assert conf.freshness == "on"  # the plane is ON by default
    assert conf.freshnessSloMs == 0.0 and conf.servingStaleSloS == 0.0
    conf = ConfArguments().parse(
        ["--freshness", "off", "--freshnessSloMs", "2500",
         "--servingStaleSloS", "30"]
    )
    assert conf.freshness == "off"
    assert conf.freshnessSloMs == 2500.0 and conf.servingStaleSloS == 30.0
    with pytest.raises(SystemExit):
        ConfArguments().parse(["--freshness", "bogus"])
    with pytest.raises(SystemExit):
        ConfArguments().parse(["--freshnessSloMs", "-1"])
    with pytest.raises(SystemExit):
        ConfArguments().parse(["--servingStaleSloS", "-0.5"])
