"""Live-Twitter protocol path, exercised for real against a LOCAL server.

Covers what the reference delegates to Twitter4j (TwitterUtils.createStream,
LinearRegression.scala:44): OAuth1 HMAC-SHA1 signing (pinned by published
external test vectors), the chunked streaming HTTP client, the v1.1
delimited-JSON stream protocol (keep-alives, disconnects, HTTP 420), and the
Twitter reconnect/backoff policy. No egress: the server is in-process
http.server speaking real HTTP over loopback.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import unquote

import pytest

from twtml_tpu.streaming import oauth1
from twtml_tpu.streaming.faults import FaultInjectingSource
from twtml_tpu.streaming.httpstream import (
    RateLimitedError,
    StreamHTTPError,
    open_stream,
)
from twtml_tpu.streaming.twitter import OAUTH_KEYS, TwitterSource

# ---------------------------------------------------------------------------
# OAuth 1.0a signing — external published vectors


def test_rfc5849_example_signature():
    """RFC 5849 §1.2 temporary-credentials request (no token secret)."""
    params = [
        ("oauth_consumer_key", "dpf43f3p2l4k3l03"),
        ("oauth_signature_method", "HMAC-SHA1"),
        ("oauth_timestamp", "137131200"),
        ("oauth_nonce", "wIjqoS"),
        ("oauth_callback", "http://printer.example.com/ready"),
    ]
    sig = oauth1.sign(
        "POST", "https://photos.example.net/initiate", params,
        consumer_secret="kd94hf93k423kf44", token_secret="",
    )
    assert sig == "74KNZJeDHnMBp0EMJ9ZHt/XKycU="


def test_twitter_docs_signature_vector():
    """The worked example from Twitter's 'Creating a signature' developer
    doc (api.twitter.com/1.1/statuses/update.json)."""
    params = [
        ("status", "Hello Ladies + Gentlemen, a signed OAuth request!"),
        ("include_entities", "true"),
        ("oauth_consumer_key", "xvz1evFS4wEEPTGEFPHBog"),
        ("oauth_nonce", "kYjzVBB8Y0ZFabxSWbWovY3uYSQ2pTgmZeNu2VS4cg"),
        ("oauth_signature_method", "HMAC-SHA1"),
        ("oauth_timestamp", "1318622958"),
        ("oauth_token", "370773112-GmHxMAgYyLbNEtIKZeRNFsMKPR9EyMZeS9weJAEb"),
        ("oauth_version", "1.0"),
    ]
    sig = oauth1.sign(
        "POST", "https://api.twitter.com/1.1/statuses/update.json", params,
        consumer_secret="kAcSOqF21Fu85e7zjz7ZN2U4ZRhfV3WpwPAoE3Z7kBw",
        token_secret="LswwdoUaIvS8ltyTt5jkRh4J50vUPVVHtR2YPi5kE",
    )
    assert sig == "hCtSmYh+iHYCEqBWrE7C7hYmtUk="


def test_percent_encoding_rfc3986():
    assert oauth1.percent_encode("Ladies + Gentlemen") == "Ladies%20%2B%20Gentlemen"
    assert oauth1.percent_encode("safe-chars_are.kept~") == "safe-chars_are.kept~"
    assert oauth1.percent_encode("☃") == "%E2%98%83"  # UTF-8 bytes, uppercase hex


def test_authorization_header_query_params_signed_not_emitted():
    hdr = oauth1.authorization_header(
        "GET", "http://example.com/stream.json?delimited=length&x=a%20b",
        consumer_key="ck", consumer_secret="cs", token="tk", token_secret="ts",
        nonce="fixednonce", timestamp=1700000000,
    )
    assert hdr.startswith("OAuth ")
    assert "delimited" not in hdr  # query params signed but not in header
    fields = dict(
        p.split("=", 1) for p in hdr[len("OAuth ") :].split(", ")
    )
    assert fields["oauth_consumer_key"] == '"ck"'
    assert fields["oauth_signature_method"] == '"HMAC-SHA1"'
    # signature must cover the DECODED query values re-encoded once
    expected = oauth1.sign(
        "GET", "http://example.com/stream.json?delimited=length&x=a%20b",
        [
            ("oauth_consumer_key", "ck"),
            ("oauth_nonce", "fixednonce"),
            ("oauth_signature_method", "HMAC-SHA1"),
            ("oauth_timestamp", "1700000000"),
            ("oauth_token", "tk"),
            ("oauth_version", "1.0"),
            ("delimited", "length"),
            ("x", "a b"),
        ],
        "cs", "ts",
    )
    assert unquote(fields["oauth_signature"].strip('"')) == expected


# ---------------------------------------------------------------------------
# Local v1.1-protocol stream server

TWEETS = [
    json.dumps({
        "text": f"RT @u: tweet {i}",
        "retweeted_status": {
            "text": f"tweet {i}",
            "retweet_count": 100 + i,
            "user": {"followers_count": 10 * i},
        },
    })
    for i in range(40)
]


class StreamHandler(BaseHTTPRequestHandler):
    """Speaks the v1.1 stream shape: 200 + chunked delimited JSON with
    keep-alive blank lines, chunk boundaries deliberately misaligned with
    line boundaries. Behavior per path:

    - /stream           : all tweets, clean end (0-chunk terminator)
    - /drop             : half the tweets, then a hard disconnect (no
                          terminator) — next request serves the rest
    - /calm             : HTTP 420
    - /forbidden        : HTTP 401
    - /soak             : 10 tweets per connection, forever
    """

    protocol_version = "HTTP/1.1"
    server_state: dict = {}

    def log_message(self, *a):  # quiet
        pass

    def _start_stream(self):
        self.send_response(200)
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Content-Type", "application/json")
        self.end_headers()

    def _send_raw(self, data: bytes, chunk: int = 37):
        """Write as chunked frames of ``chunk`` bytes — misaligned with the
        JSON lines so the client must reassemble across chunks."""
        for i in range(0, len(data), chunk):
            piece = data[i : i + chunk]
            self.wfile.write(f"{len(piece):x}\r\n".encode() + piece + b"\r\n")
        self.wfile.flush()

    def do_GET(self):
        self.server_state.setdefault("auth_headers", []).append(
            self.headers.get("Authorization", "")
        )
        if self.path == "/calm":
            self.send_response(420, "Enhance Your Calm")
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        if self.path == "/forbidden":
            self.send_response(401, "Unauthorized")
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self._start_stream()
        if self.path == "/stream":
            body = "\r\n".join(TWEETS[:20]) + "\r\n\r\n\r\n"  # 2 keep-alives
            self._send_raw(body.encode())
            self.wfile.write(b"0\r\n\r\n")  # clean terminator
        elif self.path == "/drop":
            n = self.server_state.setdefault("drop_conns", 0)
            self.server_state["drop_conns"] = n + 1
            if n == 0:
                self._send_raw(("\r\n".join(TWEETS[:10]) + "\r\n").encode())
                # hard disconnect: no terminating chunk; abort the socket
                self.connection.close()
                raise ConnectionAbortedError  # stop handler, keep server
            self._send_raw(("\r\n".join(TWEETS[10:20]) + "\r\n").encode())
            self.wfile.write(b"0\r\n\r\n")
        elif self.path == "/soak":
            n = self.server_state.setdefault("soak_conns", 0)
            self.server_state["soak_conns"] = n + 1
            lo = (n * 10) % len(TWEETS)
            self._send_raw(("\r\n".join(TWEETS[lo : lo + 10]) + "\r\n").encode())
            self.wfile.write(b"0\r\n\r\n")
        self.close_connection = True


@pytest.fixture()
def stream_server():
    StreamHandler.server_state = {}
    server = ThreadingHTTPServer(("127.0.0.1", 0), StreamHandler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        yield f"http://127.0.0.1:{server.server_address[1]}"
    finally:
        server.shutdown()
        server.server_close()


CREDS = {k: "secret-" + k.rsplit(".", 1)[1] for k in OAUTH_KEYS}


def _collect(src: TwitterSource, expect: int, timeout: float = 15.0):
    got = []
    src.start(got.append)
    deadline = time.time() + timeout
    while len(got) < expect and not src.exhausted and time.time() < deadline:
        time.sleep(0.01)
    src.stop()
    return got


def test_real_http_stream_end_to_end(stream_server):
    """Full native path: OAuth header → HTTP request → chunked decode →
    line reassembly → Status parse. No connect_fn anywhere."""
    src = TwitterSource(CREDS, url=stream_server + "/stream")
    got = _collect(src, 20)
    assert len(got) == 20
    assert [s.retweeted_status.retweet_count for s in got] == list(range(100, 120))
    # the server saw a well-formed signed Authorization header
    auth = StreamHandler.server_state["auth_headers"][0]
    assert auth.startswith("OAuth ")
    for field in ("oauth_consumer_key", "oauth_nonce", "oauth_signature",
                  "oauth_timestamp", "oauth_token", "oauth_version"):
        assert field in auth


def test_server_side_signature_verifies(stream_server):
    """Recompute the signature server-side from the received header — proves
    the header's params and the signature agree end-to-end (the signing
    primitive itself is pinned by the external vectors above)."""
    url = stream_server + "/stream"
    src = TwitterSource(CREDS, url=url)
    _collect(src, 20)
    auth = StreamHandler.server_state["auth_headers"][0]
    fields = {
        k: unquote(v.strip('"'))
        for k, v in (p.split("=", 1) for p in auth[len("OAuth ") :].split(", "))
    }
    claimed = fields.pop("oauth_signature")
    recomputed = oauth1.sign(
        "GET", url, sorted(fields.items()),
        consumer_secret=CREDS["twitter4j.oauth.consumerSecret"],
        token_secret=CREDS["twitter4j.oauth.accessTokenSecret"],
    )
    assert claimed == recomputed


def test_disconnect_reconnects_and_resumes(stream_server):
    """Mid-stream hard disconnect → supervisor restarts with the transport
    backoff → second connection serves the remainder."""
    src = TwitterSource(CREDS, url=stream_server + "/drop")
    got = _collect(src, 20)
    assert StreamHandler.server_state["drop_conns"] == 2
    counts = [s.retweeted_status.retweet_count for s in got]
    assert counts == list(range(100, 120))


def test_http_420_raises_rate_limited(stream_server):
    with pytest.raises(RateLimitedError) as exc:
        list(open_stream(stream_server + "/calm"))
    assert exc.value.status == 420


def test_http_401_raises_stream_error(stream_server):
    with pytest.raises(StreamHTTPError) as exc:
        list(open_stream(stream_server + "/forbidden"))
    assert exc.value.status == 401
    assert not isinstance(exc.value, RateLimitedError)


def test_backoff_policy_matches_twitter_rules():
    src = TwitterSource(CREDS)
    # 420: exponential from 60s
    assert src._backoff(RateLimitedError(420), 1) == 60.0
    assert src._backoff(RateLimitedError(420), 2) == 120.0
    # other HTTP: exponential from 5s, cap 320
    assert src._backoff(StreamHTTPError(503), 1) == 5.0
    assert src._backoff(StreamHTTPError(503), 2) == 10.0
    assert src._backoff(StreamHTTPError(503), 10) == 320.0
    # transport: linear 250ms, cap 16s
    assert src._backoff(ConnectionError(), 1) == 0.25
    assert src._backoff(ConnectionError(), 4) == 1.0
    assert src._backoff(ConnectionError(), 100) == 16.0


def test_fault_injected_live_stream_soak(stream_server):
    """VERDICT r1 done-criterion: fault-injected fake-stream soak. The
    injector crashes the receiver every 17 tweets on top of the server
    ending every connection after 10 — both recovery paths interleave."""
    inner = TwitterSource(CREDS, url=stream_server + "/soak")
    src = FaultInjectingSource(inner, crash_every=17, max_crashes=3)
    got = _collect(src, 100, timeout=30.0)
    assert len(got) >= 100
    assert src.crashes == 3
    assert StreamHandler.server_state["soak_conns"] >= 10


def test_keep_alive_lines_skipped(stream_server):
    """/stream embeds blank keep-alive lines; none become Status objects."""
    src = TwitterSource(CREDS, url=stream_server + "/stream")
    got = _collect(src, 20)
    assert all(s.text for s in got)


# ---------------------------------------------------------------------------
# r5: multi-host live intake (id-residue sharding) + live block ingest


def _corpus_lines(n=40):
    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    lines = []
    for i, s in enumerate(
        SyntheticSource(total=n, seed=13, base_ms=1785320000000).produce()
    ):
        d = _status_json(s)
        d["id"] = 1000 + i  # snowflake ids — the shard key
        lines.append(json.dumps(d))
    return lines


def test_id_sharded_live_intake_disjoint_and_complete():
    """VERDICT r4 #8: every host opens its own connection to the SAME
    stream and keeps rows with id ≡ processId (mod N) — shard-disjoint,
    union-complete, through the real protocol path (N concurrent
    connections against the local v1.1 server)."""
    from tools.localstream import LocalV11StreamServer
    from twtml_tpu.streaming.sources import IdShardedSource
    from twtml_tpu.streaming.twitter import TwitterSource

    lines = _corpus_lines(40)
    all_ids = set(range(1000, 1040))
    with LocalV11StreamServer(lines) as server:
        shard_ids = []
        for pid in range(2):
            src = IdShardedSource(
                TwitterSource(CREDS, url=server.url), pid, 2
            )
            got = _collect(src, 20)
            assert len(got) >= 20
            shard_ids.append({s.id for s in got})
    assert shard_ids[0] & shard_ids[1] == set()
    assert shard_ids[0] | shard_ids[1] == all_ids
    for pid in (0, 1):
        assert all(i % 2 == pid for i in shard_ids[pid])


def test_id_shard_wrapper_keeps_live_backoff_policy():
    from twtml_tpu.streaming.httpstream import RateLimitedError
    from twtml_tpu.streaming.sources import IdShardedSource
    from twtml_tpu.streaming.twitter import TwitterSource

    inner = TwitterSource(CREDS)
    shard = IdShardedSource(inner, 0, 2)
    assert shard.max_restarts == inner.max_restarts
    assert shard._backoff(RateLimitedError(420, ""), 1) == 60.0


def test_block_twitter_source_matches_object_path():
    """r5 live --ingest block: raw stream lines → native C parser →
    ParsedBlocks, byte-identical featurized batches vs the per-line
    json.loads Status path (config #2's host bottleneck deleted)."""
    import numpy as np

    from tools.localstream import LocalV11StreamServer
    from twtml_tpu.features.blocks import merge_blocks, slice_block
    from twtml_tpu.features.featurizer import Featurizer, Status
    from twtml_tpu.streaming.twitter import BlockTwitterSource

    lines = _corpus_lines(40)
    statuses = [Status.from_json(json.loads(ln)) for ln in lines]
    feat = Featurizer(now_ms=1785320000000)

    blocks = []
    with LocalV11StreamServer(lines) as server:
        src = BlockTwitterSource(
            CREDS, url=server.url, flush_seconds=0.05,
        )
        src.start(blocks.append)
        deadline = time.time() + 20.0
        while (
            sum(b.rows for b in blocks) < 40 and time.time() < deadline
        ):
            time.sleep(0.01)
        src.stop()
    merged = merge_blocks(list(blocks))
    assert merged.rows >= 40
    first = slice_block(merged, 0, 40)

    obj = feat.featurize_batch_units(statuses, row_bucket=64, unit_bucket=128)
    blk = feat.featurize_parsed_block(first, row_bucket=64, unit_bucket=128)
    np.testing.assert_array_equal(obj.units, blk.units)
    np.testing.assert_array_equal(obj.length, blk.length)
    np.testing.assert_allclose(obj.numeric, blk.numeric, rtol=1e-6)
    np.testing.assert_array_equal(obj.label, blk.label)
    np.testing.assert_array_equal(obj.mask, blk.mask)
