"""The shipped dashboard JavaScript, EXECUTED (VERDICT r1 #5).

The reference declared browser tests and commented them out
(WebTestSuite.scala:7,44-52); this build image has no JS runtime at all, so
these tests run the REAL asset files (web/assets/js/*.js, untouched) on the
in-repo jsmini interpreter (tools/jsmini.py) against a stub DOM whose
elements come from the REAL index.html/test.html id attributes
(tools/jsdom.py). A broken jsonClass dispatch, a renamed counter id, or a
syntax error in any shipped asset fails here. Parsing every file also
replaces the reference's sbt-jshint asset lint (web/build.sbt:25-39).
"""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.jsdom import Harness  # noqa: E402
from tools.jsmini import parse  # noqa: E402

ASSETS = os.path.join(REPO, "twtml_tpu", "web", "assets")
JS = os.path.join(ASSETS, "js")
ALL_JS = ["api.js", "chart.js", "index.js", "test.js"]


def js_path(name):
    return os.path.join(JS, name)


# ---------------------------------------------------------------------------
# lint: every shipped asset parses (the sbt-jshint analog)

@pytest.mark.parametrize("name", ALL_JS)
def test_shipped_js_parses(name):
    with open(js_path(name), encoding="utf-8") as fh:
        parse(fh.read())


# ---------------------------------------------------------------------------
# dashboard page (index.html + api.js + chart.js + index.js)

def dashboard(defer_series=False):
    h = Harness([os.path.join(ASSETS, "index.html")])
    h.fetch_routes["/api/stats"] = {
        "jsonClass": "Stats", "count": 0, "batch": 0, "mse": 0,
        "realStddev": 0, "predStddev": 0,
    }
    h.fetch_routes["/api/hosts"] = {
        "jsonClass": "Hosts", "hosts": [], "straggler": -1, "stage": "",
        "skewMs": 0.0,
    }
    h.fetch_routes["/api/tenants"] = {
        "jsonClass": "Tenants", "tenants": [], "gating": -1, "active": 0,
    }
    h.fetch_routes["/api/model"] = {
        "jsonClass": "ModelHealth", "level": "ok", "driftScore": 0.0,
        "lossTrend": 0.0, "weightNorm": 0.0, "updateNorm": 0.0,
        "gradNorm": 0.0, "mse": [], "tenants": [], "episodes": 0,
    }
    h.fetch_routes["/api/serving"] = {
        "jsonClass": "Serving", "qps": 0.0, "rowsPerSec": 0.0,
        "p50Ms": 0.0, "p95Ms": 0.0, "p99Ms": 0.0, "snapshotStep": -1,
        "level": "", "requests": 0, "rows": 0, "errors": 0, "tenants": [],
    }
    h.fetch_routes["/api/fleet"] = {
        "jsonClass": "Fleet", "policy": "", "replicas": [], "requests": 0,
        "retries": 0, "ejections": 0, "champion": -1,
    }
    h.fetch_routes["/api/freshness"] = {
        "jsonClass": "Freshness", "batches": 0, "rows": 0, "eventLagMs": -1.0,
        "eventLagP50Ms": -1.0, "eventLagP95Ms": -1.0, "eventLagP99Ms": -1.0,
        "publishLagP95Ms": -1.0, "watermarkLagMs": -1.0, "watermark": [],
        "critical": "", "criticalTicks": {}, "sloMs": 0.0, "breachRun": 0,
        "breaches": 0,
    }
    series = h.defer("/api/series") if defer_series else None
    if not defer_series:
        h.fetch_routes["/api/series"] = []
    for name in ("api.js", "chart.js", "index.js"):
        h.load_script(js_path(name))
    h.dom_content_loaded()
    return (h, series) if defer_series else h


def frame(**kw):
    return json.dumps(kw)


def test_boot_opens_websocket_and_backfills():
    h = dashboard()
    assert len(h.websockets) == 1
    assert h.ws.url == "ws://localhost:8888/api"
    urls = [u for u, _ in h.fetches]
    assert "/api/stats" in urls and "/api/series" in urls


def test_socket_badge_lifecycle():
    h = dashboard()
    h.ws.server_open()
    assert h.el("conn").text == "live"
    assert "live" in h.el("conn").class_set
    h.ws.server_close()
    assert h.el("conn").text == "offline"
    assert "live" not in h.el("conn").class_set


def test_stats_frame_updates_all_five_counters():
    """The five counter ids are the reference's wire contract
    (index.html:46-67, js/index.js:55-61)."""
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Stats", count=1234567, batch=678, mse=4321,
        realStddev=15, predStddev=25,
    ))
    assert h.el("count").text == "1,234,567"  # toLocaleString
    assert h.el("batch").text == "678"
    assert h.el("mse").text == "4,321"
    assert h.el("realStddev").text == "15"
    assert h.el("predStddev").text == "25"


def test_config_frame_resets_counters_and_rebuilds_iframes():
    """Config: counters reset, session label set, one iframe per viz id with
    the reference's pym URL shape (js/index.js:35-43)."""
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Stats", count=9, batch=9, mse=9, realStddev=9, predStddev=9,
    ))
    h.ws.server_message(frame(
        jsonClass="Config", id="sess-1", host="http://lightning",
        viz=["101", "102"],
    ))
    for el_id in ("count", "batch", "mse", "realStddev", "predStddev"):
        assert h.el(el_id).text == "0"
    assert h.el("session").text == "sess-1"
    frames = h.el("graphs").children
    assert [f.tag for f in frames] == ["iframe", "iframe"]
    assert [f.get("src") for f in frames] == [
        "http://lightning/visualizations/101/pym",
        "http://lightning/visualizations/102/pym",
    ]


def test_metrics_frame_updates_observability_panel():
    """Metrics frames (telemetry/metrics.py snapshots) drive the pipeline
    panel: tunnel badge with phase class, rtt, wire MB, rss, fetch depth."""
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Metrics",
        counters={"wire.bytes": 2500000},
        gauges={"host.rss_mb": 512.5, "fetch.queue_depth": 7},
        health={"phase": "degraded", "rtt_ms": 412.5, "transitions": 3},
    ))
    assert h.el("tunnelPhase").text == "degraded"
    assert "degraded" in h.el("tunnelPhase").class_set
    assert h.el("rttMs").text == "412.5"
    assert h.el("wireMb").text == "2.5"
    assert h.el("rssMb").text == "512.5"
    assert h.el("fetchDepth").text == "7"
    assert h.el("phaseFlips").text == "3"
    # recovery flips the badge class back
    h.ws.server_message(frame(
        jsonClass="Metrics", counters={}, gauges={},
        health={"phase": "healthy", "rtt_ms": 71.0, "transitions": 4},
    ))
    assert h.el("tunnelPhase").text == "healthy"
    assert "healthy" in h.el("tunnelPhase").class_set
    assert "degraded" not in h.el("tunnelPhase").class_set


def test_metrics_frame_updates_ingest_guard_tiles():
    """r7 ingest/state robustness tiles: queue depth (rows), shed rows,
    and sentinel rollbacks (highlighted once any occurred)."""
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Metrics",
        counters={"ingest.rows_shed": 4096, "model.rollbacks": 2},
        gauges={"ingest.queue_rows": 12288},
        health={"phase": "healthy", "rtt_ms": 70.0, "transitions": 0},
    ))
    assert h.el("queueRows").text == "12288"
    assert h.el("rowsShed").text == "4096"
    assert h.el("rollbacks").text == "2"
    assert "degraded" in h.el("rollbacks").class_set
    # a healthy run keeps the tile quiet
    h.ws.server_message(frame(
        jsonClass="Metrics", counters={}, gauges={},
        health={"phase": "healthy", "rtt_ms": 70.0, "transitions": 0},
    ))
    assert h.el("rollbacks").text == "0"
    assert "degraded" not in h.el("rollbacks").class_set


def test_metrics_frame_updates_journal_tile():
    """ISSUE 19 intake journal: the journal.replayed_rows counter renders on
    the 'journal · replayed' tile — nonzero means a recovery path replayed
    rows instead of counting them lost; a frame without it resets to 0."""
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Metrics",
        counters={"journal.replayed_rows": 2048},
        gauges={"journal.disk_mb": 12.5},
        health={"phase": "healthy", "rtt_ms": 70.0, "transitions": 0},
    ))
    assert h.el("journalReplayed").text == "2048"
    h.ws.server_message(frame(
        jsonClass="Metrics", counters={}, gauges={},
        health={"phase": "healthy", "rtt_ms": 70.0, "transitions": 0},
    ))
    assert h.el("journalReplayed").text == "0"


def test_metrics_frame_updates_wire_ratio_tile():
    """r15 compressed wire: the wire.codec_ratio gauge (raw/compressed
    units bytes, apps/common._record_wire_codec) renders on the pipeline
    panel; a frame without it resets the tile to 1.00 (codec off)."""
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Metrics",
        counters={"wire.codec_fallbacks": 0},
        gauges={"wire.codec_ratio": 1.472,
                "wire.units_compressed_bytes": 11264},
        health={"phase": "healthy", "rtt_ms": 70.0, "transitions": 0},
    ))
    assert h.el("wireRatio").text == "1.47"
    h.ws.server_message(frame(
        jsonClass="Metrics", counters={}, gauges={},
        health={"phase": "healthy", "rtt_ms": 70.0, "transitions": 0},
    ))
    assert h.el("wireRatio").text == "1.00"


def test_metrics_frame_updates_latency_tile():
    """r8: the derived fetch-latency p95 (Metrics.histograms, seconds)
    renders in ms on the pipeline panel."""
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Metrics", counters={}, gauges={},
        health={"phase": "healthy", "rtt_ms": 70.0, "transitions": 0},
        histograms={"fetch.latency_s": {"count": 9, "mean": 0.07,
                    "p50": 0.064, "p95": 0.128, "p99": 0.256}},
    ))
    assert h.el("fetchP95").text == "128.0"
    # a Metrics frame without histograms resets the tile, never throws
    h.ws.server_message(frame(
        jsonClass="Metrics", counters={}, gauges={},
        health={"phase": "healthy", "rtt_ms": 70.0, "transitions": 0},
    ))
    assert h.el("fetchP95").text == "0.0"


def test_hosts_frame_builds_tiles_and_names_straggler():
    """r8 Hosts tiles: one tile per host from the sideband view, the
    gating host highlighted with the ladder stage, tick skew shown."""
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Hosts",
        hosts=[{"host": 0, "tick_prep_ms": 12.4},
               {"host": 1, "tick_prep_ms": 141.7}],
        straggler=1, stage="upload", skewMs=129.3,
    ))
    assert h.el("straggler").text == "host 1 · upload"
    assert "degraded" in h.el("straggler").class_set
    assert h.el("tickSkew").text == "129.3"
    tiles = h.el("hostsPanel").children
    assert len(tiles) == 2
    labels = [t.children[0].text for t in tiles]
    values = [t.children[1].text for t in tiles]
    assert labels == ["host 0", "host 1 · gating"]
    assert values == ["12 ms", "142 ms"]
    assert "gating" in tiles[1].class_set
    assert "gating" not in tiles[0].class_set
    # a healthy tick clears the highlight and rebuilds the tiles
    h.ws.server_message(frame(
        jsonClass="Hosts",
        hosts=[{"host": 0, "tick_prep_ms": 10.0},
               {"host": 1, "tick_prep_ms": 11.0}],
        straggler=-1, stage="", skewMs=1.0,
    ))
    assert h.el("straggler").text == "—"
    assert "degraded" not in h.el("straggler").class_set
    tiles = h.el("hostsPanel").children
    assert all("gating" not in t.class_set for t in tiles)


def test_hosts_frame_elastic_tile_shows_epoch_hosts_and_lead():
    """r20 lead election: the elastic tile names the CURRENT lead next to
    the epoch + live-host count (it moves only at a won election), and a
    non-elastic run (epoch -1 / leadUid -1) keeps the dashes."""
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Hosts", hosts=[], straggler=-1, stage="", skewMs=0.0,
        epoch=2, liveHosts=3, leadUid=1, departed=1, rejoined=0,
    ))
    assert h.el("elasticEpoch").text == "2 · 3 hosts · lead 1"
    assert h.el("elasticChurn").text == "1 / 0"
    # a post-election 1-host epoch: singular "host", the winner as lead
    h.ws.server_message(frame(
        jsonClass="Hosts", hosts=[], straggler=-1, stage="", skewMs=0.0,
        epoch=1, liveHosts=1, leadUid=1, departed=1, rejoined=0,
    ))
    assert h.el("elasticEpoch").text == "1 · 1 host · lead 1"
    # not elastic: epoch/leadUid -1 → dashes, no stray "lead" text
    h.ws.server_message(frame(
        jsonClass="Hosts", hosts=[], straggler=-1, stage="", skewMs=0.0,
        epoch=-1, liveHosts=0, leadUid=-1, departed=0, rejoined=0,
    ))
    assert h.el("elasticEpoch").text == "—"
    assert h.el("elasticChurn").text == "—"


def test_tenants_frame_builds_tiles_and_highlights_gating():
    """r10 Tenants tiles (ISSUE 7): one tile per tenant from the model-
    plane view, the gating (busiest) tenant highlighted, active count
    shown as active/configured."""
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Tenants",
        tenants=[{"tenant": 0, "rows": 1200, "batch": 96, "mse": 1234.5},
                 {"tenant": 1, "rows": 800, "batch": 0, "mse": -1.0},
                 {"tenant": 2, "rows": 2100, "batch": 160, "mse": 88.0}],
        gating=2, active=2,
    ))
    assert h.el("tenantsActive").text == "2 / 3"
    tiles = h.el("tenantsPanel").children
    assert len(tiles) == 3
    labels = [t.children[0].text for t in tiles]
    values = [t.children[1].text for t in tiles]
    assert labels == ["tenant 0", "tenant 1", "tenant 2 · gating"]
    # rows localized + mse shown only when finite (-1 = no finite sample)
    assert values == ["1,200 · mse 1235", "800", "2,100 · mse 88"]
    assert "gating" in tiles[2].class_set
    assert all("gating" not in t.class_set for t in tiles[:2])
    # an all-dry tick clears the highlight
    h.ws.server_message(frame(
        jsonClass="Tenants",
        tenants=[{"tenant": 0, "rows": 1200, "batch": 0, "mse": -1.0}],
        gating=-1, active=0,
    ))
    tiles = h.el("tenantsPanel").children
    assert all("gating" not in t.class_set for t in tiles)


def test_model_health_frame_updates_tiles_and_level_class():
    """r11 "model · drift" tiles (ISSUE 8): health badge with graduated
    level class, drift z / loss-trend / norm values, episode counter."""
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="ModelHealth", level="warn", driftScore=5.26,
        lossTrend=0.31, weightNorm=122.6, updateNorm=3.14, gradNorm=4400.0,
        mse=[100.0, 110.0, 130.0], tenants=[], episodes=2,
    ))
    assert h.el("modelLevel").text == "warn"
    assert "warn" in h.el("modelLevel").class_set
    assert "ok" not in h.el("modelLevel").class_set
    assert h.el("driftScore").text == "5.3"
    assert h.el("lossTrend").text == "+31%"
    assert h.el("weightNorm").text == "122.6"
    assert h.el("updateNorm").text == "3.14"
    assert h.el("driftEpisodes").text == "2"
    # recovery flips the badge class back to ok
    h.ws.server_message(frame(
        jsonClass="ModelHealth", level="ok", driftScore=0.4, lossTrend=-0.02,
        weightNorm=123.0, updateNorm=1.0, gradNorm=4000.0, mse=[100.0],
        tenants=[], episodes=2,
    ))
    assert h.el("modelLevel").text == "ok"
    assert "ok" in h.el("modelLevel").class_set
    assert "warn" not in h.el("modelLevel").class_set
    assert h.el("lossTrend").text == "-2%"


def test_model_health_tenant_tiles_highlight_unhealthy():
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="ModelHealth", level="alert", driftScore=9.5, lossTrend=0.0,
        weightNorm=10.0, updateNorm=1.0, gradNorm=100.0, mse=[1.0],
        tenants=[{"tenant": 0, "level": "ok", "drift": 0.3},
                 {"tenant": 1, "level": "alert", "drift": 9.5}],
        episodes=1,
    ))
    tiles = h.el("modelTenantsPanel").children
    assert len(tiles) == 2
    labels = [t.children[0].text for t in tiles]
    values = [t.children[1].text for t in tiles]
    assert labels == ["tenant 0", "tenant 1"]
    assert values == ["ok · z 0.3", "alert · z 9.5"]
    assert "alerting" in tiles[1].class_set
    assert "alerting" not in tiles[0].class_set
    # a healthy frame clears the tiles' highlight
    h.ws.server_message(frame(
        jsonClass="ModelHealth", level="ok", driftScore=0.2, lossTrend=0.0,
        weightNorm=10.0, updateNorm=1.0, gradNorm=100.0, mse=[1.0],
        tenants=[{"tenant": 0, "level": "ok", "drift": 0.2}], episodes=1,
    ))
    tiles = h.el("modelTenantsPanel").children
    assert all("alerting" not in t.class_set for t in tiles)


def test_model_health_loss_sparkline_draws():
    h = dashboard()
    h.ws.server_open()
    ctx = h.el("lossSpark").ctx
    ctx.calls.clear()
    h.ws.server_message(frame(
        jsonClass="ModelHealth", level="ok", driftScore=0.0, lossTrend=0.0,
        weightNorm=1.0, updateNorm=1.0, gradNorm=1.0,
        mse=[100.0, 120.0, 90.0, 130.0], tenants=[], episodes=0,
    ))
    assert len(ctx.ops("stroke")) == 1
    assert len(ctx.ops("lineTo")) == 3  # 4 points: 1 moveTo + 3 lineTo
    texts = [args[0] for op, args in ctx.ops("fillText")]
    assert any("130" in t for t in texts)  # last mse labeled
    # an empty window renders the placeholder, never throws
    ctx.calls.clear()
    h.ws.server_message(frame(
        jsonClass="ModelHealth", level="ok", driftScore=0.0, lossTrend=0.0,
        weightNorm=1.0, updateNorm=1.0, gradNorm=1.0, mse=[], tenants=[],
        episodes=0,
    ))
    assert len(ctx.ops("stroke")) == 0
    texts = [args[0] for op, args in ctx.ops("fillText")]
    assert any("waiting" in t for t in texts)


def test_model_health_empty_view_is_placeholder():
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="ModelHealth", level="ok", driftScore=0.0, lossTrend=0.0,
        weightNorm=0.0, updateNorm=0.0, gradNorm=0.0, mse=[], tenants=[],
        episodes=0,
    ))
    assert h.el("modelTenantsPanel").children == []


def test_tenants_empty_view_is_placeholder():
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(jsonClass="Tenants", tenants=[], gating=-1,
                              active=0))
    assert h.el("tenantsActive").text == "—"
    assert h.el("tenantsPanel").children == []


def test_metrics_backfill_fetched_on_boot():
    h = dashboard()
    urls = [u for u, _ in h.fetches]
    assert "/api/metrics" in urls
    assert "/api/hosts" in urls
    assert "/api/tenants" in urls
    assert "/api/model" in urls
    assert "/api/serving" in urls
    assert "/api/fleet" in urls


# ---------------------------------------------------------------------------
# serving plane tiles (ISSUE 9, mirrors the Hosts/Tenants suites)

def test_serving_frame_updates_tiles_and_level_badge():
    """Serving tiles: QPS/latency numbers, the active snapshot id, the
    snapshot-health badge class, and the error highlight."""
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Serving", qps=512.46, rowsPerSec=8200.0, p50Ms=8.24,
        p95Ms=61.0, p99Ms=84.06, snapshotStep=640, level="warn",
        requests=10000, rows=160000, errors=0, tenants=[],
    ))
    assert h.el("serveQps").text == "512.5"
    assert h.el("serveRows").text == "8,200"
    assert h.el("serveP50").text == "8.2"
    assert h.el("serveP99").text == "84.1"
    assert h.el("serveSnapshot").text == "ckpt-640"
    assert h.el("serveLevel").text == "warn"
    assert "warn" in h.el("serveLevel").class_set
    assert "ok" not in h.el("serveLevel").class_set
    assert h.el("serveErrors").text == "0"
    assert "degraded" not in h.el("serveErrors").class_set


def test_serving_frame_errors_highlight_and_tenant_tiles():
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Serving", qps=10.0, rowsPerSec=160.0, p50Ms=5.0,
        p95Ms=9.0, p99Ms=12.0, snapshotStep=8, level="ok",
        requests=50, rows=800, errors=3,
        tenants=[{"tenant": 0, "rows": 500}, {"tenant": 1, "rows": 300}],
    ))
    assert "ok" in h.el("serveLevel").class_set
    assert h.el("serveErrors").text == "3"
    assert "degraded" in h.el("serveErrors").class_set
    tiles = h.el("servingTenantsPanel").children
    assert len(tiles) == 2
    assert tiles[0].children[0].text == "tenant 0"
    assert tiles[0].children[1].text == "500 rows"
    assert tiles[1].children[1].text == "300 rows"


def test_serving_empty_view_is_placeholder():
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Serving", qps=0.0, rowsPerSec=0.0, p50Ms=0.0, p95Ms=0.0,
        p99Ms=0.0, snapshotStep=-1, level="", requests=0, rows=0, errors=0,
        tenants=[],
    ))
    assert h.el("serveQps").text == "—"
    assert h.el("serveSnapshot").text == "—"
    assert h.el("serveLevel").text == "—"
    assert h.el("servingTenantsPanel").children == []


# ---------------------------------------------------------------------------
# read-fleet tiles (ISSUE 11, mirrors the Serving suite)

def test_fleet_frame_updates_tiles_and_replica_row():
    """Fleet tiles: policy/requests/retries/ejections/champion numbers and
    one tile per replica, an ejected replica highlighted."""
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Fleet", policy="p99", requests=1234, retries=3,
        ejections=1, champion=2, replicas=[
            {"replica": 0, "url": "http://r0:8888", "healthy": True,
             "p99Ms": 84.4, "qps": 52.61, "requests": 700, "errors": 0,
             "ejections": 0, "snapshotStep": 640},
            {"replica": 1, "url": "http://r1:8888", "healthy": False,
             "p99Ms": 0.0, "qps": 0.0, "requests": 534, "errors": 4,
             "ejections": 1, "snapshotStep": 640},
        ],
    ))
    assert h.el("fleetPolicy").text == "p99"
    assert h.el("fleetRequests").text == "1,234"
    assert h.el("fleetRetries").text == "3"
    assert "degraded" in h.el("fleetRetries").class_set
    assert h.el("fleetEjections").text == "1"
    assert "degraded" in h.el("fleetEjections").class_set
    assert h.el("fleetChampion").text == "tenant 2"
    tiles = h.el("fleetPanel").children
    assert len(tiles) == 2
    assert tiles[0].children[0].text == "replica 0"
    assert tiles[0].children[1].text == "52.6 qps · p99 84 ms"
    assert "ejected" not in tiles[0].class_set
    assert tiles[1].children[0].text == "replica 1 · ejected"
    assert "ejected" in tiles[1].class_set


def test_fleet_empty_view_is_placeholder():
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Fleet", policy="", replicas=[], requests=0, retries=0,
        ejections=0, champion=-1,
    ))
    assert h.el("fleetPolicy").text == "—"
    assert h.el("fleetChampion").text == "—"
    assert h.el("fleetRetries").text == "0"
    assert "degraded" not in h.el("fleetRetries").class_set
    assert h.el("fleetPanel").children == []


# ---------------------------------------------------------------------------
# freshness plane tiles (ISSUE 16, mirrors the Serving suite)

def test_freshness_frame_updates_tiles_and_sparkline():
    """Freshness tiles: event-lag percentiles, publish lag, watermark lag,
    the dominant critical-path edge, breach highlight, and the watermark
    sparkline drawn from the rolling window."""
    h = dashboard()
    h.ws.server_open()
    ctx = h.el("freshSpark").ctx
    ctx.calls.clear()
    h.ws.server_message(frame(
        jsonClass="Freshness", batches=42, rows=84000, eventLagMs=812.0,
        eventLagP50Ms=640.4, eventLagP95Ms=812.6, eventLagP99Ms=1500.0,
        publishLagP95Ms=990.0, watermarkLagMs=870.0,
        watermark=[800.0, 850.0, 870.0], critical="dispatch",
        criticalTicks={"dispatch": 30, "parse": 12}, sloMs=0.0,
        breachRun=0, breaches=2,
    ))
    assert h.el("freshP50").text == "640"
    assert h.el("freshP95").text == "813"
    assert h.el("freshP99").text == "1500"
    assert h.el("freshPublish").text == "990"
    assert h.el("freshWatermark").text == "870"
    assert h.el("freshCritical").text == "dispatch"
    assert h.el("freshBreaches").text == "2"
    assert "degraded" in h.el("freshBreaches").class_set
    assert len(ctx.ops("stroke")) == 1
    assert len(ctx.ops("lineTo")) == 2  # 3 points: 1 moveTo + 2 lineTo
    texts = [args[0] for op, args in ctx.ops("fillText")]
    assert any("870" in t for t in texts)  # last watermark lag labeled
    # a breach-free frame clears the highlight
    h.ws.server_message(frame(
        jsonClass="Freshness", batches=43, rows=86000, eventLagMs=700.0,
        eventLagP50Ms=640.0, eventLagP95Ms=810.0, eventLagP99Ms=1400.0,
        publishLagP95Ms=980.0, watermarkLagMs=860.0, watermark=[860.0],
        critical="parse", criticalTicks={"parse": 13}, sloMs=0.0,
        breachRun=0, breaches=0,
    ))
    assert h.el("freshCritical").text == "parse"
    assert "degraded" not in h.el("freshBreaches").class_set


def test_freshness_empty_view_is_placeholder():
    h = dashboard()
    h.ws.server_open()
    ctx = h.el("freshSpark").ctx
    ctx.calls.clear()
    h.ws.server_message(frame(
        jsonClass="Freshness", batches=0, rows=0, eventLagMs=-1.0,
        eventLagP50Ms=-1.0, eventLagP95Ms=-1.0, eventLagP99Ms=-1.0,
        publishLagP95Ms=-1.0, watermarkLagMs=-1.0, watermark=[],
        critical="", criticalTicks={}, sloMs=0.0, breachRun=0, breaches=0,
    ))
    assert h.el("freshP95").text == "—"
    assert h.el("freshWatermark").text == "—"
    assert h.el("freshCritical").text == "—"
    assert h.el("freshBreaches").text == "0"
    assert len(ctx.ops("stroke")) == 0
    texts = [args[0] for op, args in ctx.ops("fillText")]
    assert any("waiting" in t for t in texts)


def test_serving_frame_updates_snapshot_age_tile():
    """ISSUE 16 serving staleness: snapshotAgeS renders next to the
    snapshot id; a frame without it (legacy sender) shows the placeholder."""
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Serving", qps=10.0, rowsPerSec=160.0, p50Ms=5.0,
        p95Ms=9.0, p99Ms=12.0, snapshotAgeS=37.4, snapshotStep=8,
        level="ok", requests=50, rows=800, errors=0, tenants=[],
    ))
    assert h.el("serveAge").text == "37"
    # no snapshot yet → placeholder regardless of the age field
    h.ws.server_message(frame(
        jsonClass="Serving", qps=0.0, rowsPerSec=0.0, p50Ms=0.0, p95Ms=0.0,
        p99Ms=0.0, snapshotAgeS=-1.0, snapshotStep=-1, level="",
        requests=0, rows=0, errors=0, tenants=[],
    ))
    assert h.el("serveAge").text == "—"


def test_metrics_frame_updates_ingest_lag_and_rss_slope_tiles():
    """ISSUE 16 satellites: the sampled ingest event-time lag (ms → s) and
    the continuous RSS-slope gauge render on the pipeline panel; a frame
    without the lag gauge keeps the placeholder."""
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Metrics", counters={},
        gauges={"ingest.event_time_lag_ms": 2500.0,
                "host.rss_slope_mb_per_min": 1.257},
        health={"phase": "healthy", "rtt_ms": 70.0, "transitions": 0},
    ))
    assert h.el("ingestLag").text == "2.5"
    assert h.el("rssSlope").text == "1.26"
    h.ws.server_message(frame(
        jsonClass="Metrics", counters={}, gauges={},
        health={"phase": "healthy", "rtt_ms": 70.0, "transitions": 0},
    ))
    assert h.el("ingestLag").text == "—"
    assert h.el("rssSlope").text == "0.00"


def test_freshness_backfill_fetched_on_boot():
    h = dashboard()
    urls = [u for u, _ in h.fetches]
    assert "/api/freshness" in urls


# ---------------------------------------------------------------------------
# telemetry-historian tiles (ISSUE 20, mirrors the Freshness suite)


def test_history_frame_updates_tiles_and_sparklines():
    """History tiles: sample count, phase (with degraded highlight), RSS +
    slope, fetch RTT, disk footprint, perfGuard regression count (with
    highlight), and the three long-horizon sparklines."""
    h = dashboard()
    h.ws.server_open()
    ctx = h.el("histRssSpark").ctx
    ctx.calls.clear()
    h.ws.server_message(frame(
        jsonClass="History", samples=42, runId=7, phase="degraded",
        rssMb=512.4, rssSlopeMbPerMin=1.257, rttMs=71.3, diskMb=3.5,
        regressions=2, rss=[500.0, 506.0, 512.4], rtt=[70.0, 72.0, 71.3],
        stageMs=[4.0, 4.5, 5.1],
    ))
    assert h.el("histSamples").text == "42"
    assert h.el("histPhase").text == "degraded"
    assert "degraded" in h.el("histPhase").class_set
    assert h.el("histRss").text == "512"
    assert h.el("histSlope").text == "1.26"
    assert h.el("histRtt").text == "71.3"
    assert h.el("histDisk").text == "3.5"
    assert h.el("histRegressions").text == "2"
    assert "degraded" in h.el("histRegressions").class_set
    assert len(ctx.ops("stroke")) == 1
    assert len(ctx.ops("lineTo")) == 2  # 3 points: 1 moveTo + 2 lineTo
    texts = [args[0] for op, args in ctx.ops("fillText")]
    assert any("512.4" in t for t in texts)  # last RSS value labeled
    # a healthy, regression-free frame clears both highlights
    h.ws.server_message(frame(
        jsonClass="History", samples=43, runId=7, phase="healthy",
        rssMb=512.0, rssSlopeMbPerMin=0.01, rttMs=70.0, diskMb=3.5,
        regressions=0, rss=[512.0], rtt=[70.0], stageMs=[4.0],
    ))
    assert "degraded" not in h.el("histPhase").class_set
    assert "degraded" not in h.el("histRegressions").class_set


def test_history_empty_view_is_placeholder():
    h = dashboard()
    h.ws.server_open()
    ctx = h.el("histRssSpark").ctx
    ctx.calls.clear()
    h.ws.server_message(frame(
        jsonClass="History", samples=0, runId=0, phase="", rssMb=0.0,
        rssSlopeMbPerMin=0.0, rttMs=0.0, diskMb=0.0, regressions=0,
        rss=[], rtt=[], stageMs=[],
    ))
    assert h.el("histSamples").text == "—"
    assert h.el("histRss").text == "—"
    assert h.el("histPhase").text == "—"
    assert h.el("histRegressions").text == "0"
    assert len(ctx.ops("stroke")) == 0
    texts = [args[0] for op, args in ctx.ops("fillText")]
    assert any("waiting" in t for t in texts)


def test_history_backfill_fetched_on_boot():
    h = dashboard()
    urls = [u for u, _ in h.fetches]
    assert "/api/history" in urls


def test_unknown_jsonclass_is_ignored():
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message(frame(jsonClass="Mystery", whatever=1))
    assert h.el("count").text == "0" or h.el("count").text == ""


def test_series_frames_drive_the_chart():
    h = dashboard()
    h.ws.server_open()
    ctx = h.el("livechart").ctx
    ctx.calls.clear()
    h.ws.server_message(frame(
        jsonClass="Series", real=[100, 200, 300], pred=[110, 190, 310],
        realStddev=15, predStddev=25,
    ))
    # 4 series drawn: real, pred, and both stdev bands
    assert len(ctx.ops("stroke")) == 4
    assert len(ctx.ops("lineTo")) > 0
    # legend labels drawn
    texts = [args[0] for op, args in ctx.ops("fillText")]
    for label in ("real", "predicted", "stdev real", "stdev pred"):
        assert label in texts


def test_live_series_buffer_until_backfill_lands():
    """Ordering contract (js/index.js:55-66): live Series frames arriving
    while the history fetch is in flight are buffered and applied AFTER the
    backfill, so the chart is chronological."""
    h, deferred = dashboard(defer_series=True)
    h.ws.server_open()
    # live frame arrives BEFORE the backfill response
    h.ws.server_message(frame(
        jsonClass="Series", real=[999], pred=[998], realStddev=1, predStddev=1,
    ))
    ctx = h.el("livechart").ctx
    ctx.calls.clear()
    # backfill resolves with history; then the pending live frame flushes
    deferred.resolve([
        {"jsonClass": "Series", "real": [1, 2], "pred": [1, 2],
         "realStddev": 0, "predStddev": 0},
    ])
    # chart drew at least twice (backfill push + flushed live push)
    assert len(ctx.ops("clearRect")) >= 2
    # a later live frame now applies immediately
    ctx.calls.clear()
    h.ws.server_message(frame(
        jsonClass="Series", real=[5], pred=[6], realStddev=0, predStddev=0,
    ))
    assert len(ctx.ops("clearRect")) == 1


def test_post_rides_websocket_when_open_else_http():
    h = dashboard()
    h.ws.server_open()
    h.interp.run("api.postStats(1, 2, 3, 4, 5);")
    h.interp.run_jobs()
    assert len(h.ws.sent) == 1
    sent = json.loads(h.ws.sent[0])
    assert sent == {"jsonClass": "Stats", "count": 1, "batch": 2, "mse": 3,
                    "realStddev": 4, "predStddev": 5}
    # close the socket: posts fall back to HTTP (reference api.js:65-79)
    h.fetch_routes["/api"] = {"status": "OK"}
    h.ws.server_close()
    before = len(h.fetches)
    h.interp.run("api.postConfig('id-1', 'http://h', ['7']);")
    h.interp.run_jobs()
    url, opts = h.fetches[before]
    assert url == "/api"
    assert opts.get("method") == "POST"
    assert json.loads(opts.get("body")) == {
        "jsonClass": "Config", "id": "id-1", "host": "http://h", "viz": ["7"],
    }


def test_reconnect_after_close_via_timer():
    h = dashboard()
    h.ws.server_open()
    h.ws.server_close()
    assert len(h.timers) == 1  # the 5s reconnect
    h.run_timers()
    assert len(h.websockets) == 2  # a fresh socket was opened


def test_websocket_off_suppresses_reconnect():
    h = dashboard()
    h.ws.server_open()
    h.interp.run("api.websocketOff();")
    h.interp.run_jobs()
    assert not h.timers  # deliberate close: no reconnect scheduled


def test_guid_shape():
    h = dashboard()
    h.interp.run("window._g = api.guid();")
    guid = h.interp.global_this.get("_g")
    import re

    assert re.fullmatch(
        r"[0-9a-f]{8}-[0-9a-f]{4}-4[0-9a-f]{3}-[89ab][0-9a-f]{3}-[0-9a-f]{12}",
        guid,
    ), guid


def test_bad_frame_does_not_kill_the_dispatcher():
    h = dashboard()
    h.ws.server_open()
    h.ws.server_message("this is not json")
    h.ws.server_message(frame(
        jsonClass="Stats", count=7, batch=7, mse=7, realStddev=7, predStddev=7,
    ))
    assert h.el("count").text == "7"
    assert any("error" in line for line in h.console)


# ---------------------------------------------------------------------------
# negative controls: the suite's sensitivity is itself tested — a broken
# dispatch or a missing counter id must change observable behavior, so the
# assertions above would fail on a real regression

def test_negative_control_broken_dispatch_is_detected(tmp_path):
    """A typo'd jsonClass case in index.js leaves the counters un-updated —
    exactly what test_stats_frame_updates_all_five_counters asserts on."""
    with open(js_path("index.js"), encoding="utf-8") as fh:
        src = fh.read()
    broken = src.replace('case "Stats":', 'case "Statz":')
    assert broken != src, "mutation site vanished; update the control"
    mutated = tmp_path / "index.js"
    mutated.write_text(broken, encoding="utf-8")

    h = Harness([os.path.join(ASSETS, "index.html")])
    h.fetch_routes["/api/stats"] = {"jsonClass": "Stats", "count": 0, "batch": 0,
                                    "mse": 0, "realStddev": 0, "predStddev": 0}
    h.fetch_routes["/api/series"] = []
    h.load_script(js_path("api.js"))
    h.load_script(js_path("chart.js"))
    h.load_script(str(mutated))
    h.dom_content_loaded()
    h.ws.server_open()
    h.ws.server_message(frame(
        jsonClass="Stats", count=42, batch=1, mse=1, realStddev=1, predStddev=1,
    ))
    assert h.el("count").text != "42"  # the regression IS observable


def test_negative_control_missing_counter_id_is_detected():
    """Removing a counter element (as a renamed id in index.html would)
    makes the Stats handler throw — the dispatcher logs it and the counter
    never updates, so the positive tests would fail."""
    h = dashboard()
    h.ws.server_open()
    del h.elements["mse"]  # simulate id="mse" missing from index.html
    h.ws.server_message(frame(
        jsonClass="Stats", count=42, batch=1, mse=7, realStddev=9, predStddev=9,
    ))
    # the handler throws at the missing element: counters after it in the
    # update order never change — test_stats_frame_updates_all_five_counters
    # would fail on exactly this
    assert h.el("realStddev").text != "9"
    assert h.el("predStddev").text != "9"
    assert any("error" in line for line in h.console)


def test_negative_control_syntax_error_is_detected(tmp_path):
    """The lint catches a syntax break (the sbt-jshint analog)."""
    with open(js_path("api.js"), encoding="utf-8") as fh:
        src = fh.read()
    mutated = tmp_path / "api.js"
    mutated.write_text(src.replace("this.ws.send(text);",
                                   "this.ws.send(text"), encoding="utf-8")
    with pytest.raises(Exception):
        with open(mutated, encoding="utf-8") as fh:
            parse(fh.read())


# ---------------------------------------------------------------------------
# manual test harness page (test.html + api.js + test.js)

def harness_page():
    h = Harness([os.path.join(ASSETS, "test.html")])
    h.fetch_routes["/api"] = {"status": "OK"}
    for name in ("api.js", "test.js"):
        h.load_script(js_path(name))
    h.dom_content_loaded()
    return h


def test_harness_ws_toggle_and_log():
    h = harness_page()
    assert not h.websockets
    h.click("wsToggle")
    assert len(h.websockets) == 1
    assert h.el("wsToggle").text == "websocket: on"
    h.ws.server_open()
    h.ws.server_message(frame(jsonClass="Stats", count=1, batch=1, mse=1,
                              realStddev=1, predStddev=1))
    # the received frame was logged into the table (time cell + json cell);
    # rows also hold the _Socket open event — find the Stats row
    log_rows = h.el("log").rows
    assert log_rows, "no rows logged"
    assert any(
        len(r.rows) >= 2 and "Stats" in r.rows[1].text for r in log_rows
    ), [r.rows[1].text for r in log_rows if len(r.rows) >= 2]
    h.click("wsToggle")
    assert h.el("wsToggle").text == "websocket: off"


def test_harness_post_config_reads_form_fields():
    h = harness_page()
    h.el("cfgId").set("value", "abc")
    h.el("cfgHost").set("value", "http://lgn")
    h.el("cfgViz").set("value", " 1, 2 ,3")
    h.click("postConfig")
    url, opts = h.fetches[-1]
    assert url == "/api"
    body = json.loads(opts.get("body"))
    assert body == {"jsonClass": "Config", "id": "abc", "host": "http://lgn",
                    "viz": ["1", "2", "3"]}  # split(",").map(trim)


def test_harness_post_stats_numbers():
    h = harness_page()
    for el_id, value in (("stCount", "10"), ("stBatch", "2"), ("stMse", "30"),
                         ("stReal", "4"), ("stPred", "5")):
        h.el(el_id).set("value", value)
    h.click("postStats")
    body = json.loads(h.fetches[-1][1].get("body"))
    assert body == {"jsonClass": "Stats", "count": 10, "batch": 2, "mse": 30,
                    "realStddev": 4, "predStddev": 5}


# ---------------------------------------------------------------------------
# dashboard snapshot artifact (doc/dashboard.svg, VERDICT r3 #8)

def test_dashboard_snapshot_tool_produces_svg(tmp_path):
    """tools/dashboard_snapshot.py: the doc artifact is the real assets
    executing over a real training run — the SVG must carry the 4 chart
    series (chart.js's stroke colors) and non-zero counter values."""
    from tools import dashboard_snapshot as snap

    out = str(tmp_path / "dash.svg")
    snap.main(["--out", out])
    svg = open(out, encoding="utf-8").read()
    for color in ("rgb(30, 144, 255)", "rgb(255, 215, 0)",
                  "rgba(173, 216, 230, 0.5)", "rgba(238, 232, 170, 0.5)"):
        assert f'stroke="{color}"' in svg  # all 4 series drawn
    assert "polyline" in svg and "TWEETS TOTAL" in svg
    assert ">live<" in svg  # websocket badge reflected
    assert ">0</text>" not in svg.split("TWEETS TOTAL")[1].split("</g>")[0]
