"""Pipeline tracing (telemetry/trace.py + tools/trace_report.py): span
nesting, crash flush, the off-by-default null tracer, trace_report's
malformed-file check — and the tier-1 integration smoke: a ``--trace`` run
of the linear-regression entry on the local replay source produces a
Perfetto-valid trace with every expected stage name and ZERO extra host
fetches vs the untraced run (the BENCHMARKS.md measurement-integrity
constraint, asserted against FetchPipeline's one-fetch-per-batch)."""

import json

import pytest

from tools import trace_report
from twtml_tpu.telemetry import trace
from twtml_tpu.telemetry import metrics as metrics_mod


@pytest.fixture(autouse=True)
def _clean_tracer():
    yield
    trace.uninstall()


def test_null_tracer_is_noop():
    tr = trace.get()
    assert not tr.enabled
    with tr.span("anything", rows=1):
        pass
    tr.instant("x")
    tr.counter("y", v=1)
    tr.close()  # all no-ops


def test_span_nesting_and_args(tmp_path):
    path = str(tmp_path / "t.trace")
    tr = trace.install(path)
    with tr.span("featurize", items=3) as sp:
        with tr.span("parse"):
            pass
        sp.add(rows=4, wire_bytes=128)
    trace.uninstall()
    events = trace_report.load_events(path)
    spans = {e["name"]: e for e in events if e.get("ph") == "X"}
    assert set(spans) == {"featurize", "parse"}
    assert spans["featurize"]["args"] == {
        "items": 3, "rows": 4, "wire_bytes": 128,
    }
    # nesting: the inner span lies within the outer span's window
    outer, inner = spans["featurize"], spans["parse"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1


def test_crash_flush_leaves_events_on_disk(tmp_path):
    """Line-buffered writes: a crash mid-run (no close()) must still leave
    every completed span on disk, and the span an exception escaped through
    is recorded with the error class."""
    path = str(tmp_path / "crash.trace")
    tr = trace.install(path)
    with pytest.raises(RuntimeError):
        with tr.span("dispatch", depth=2):
            raise RuntimeError("boom")
    # read WITHOUT closing — simulating a crashed process's file
    events = trace_report.load_events(path)
    (ev,) = [e for e in events if e.get("ph") == "X"]
    assert ev["name"] == "dispatch"
    assert ev["args"]["error"] == "RuntimeError"


def test_instant_and_counter_events(tmp_path):
    path = str(tmp_path / "i.trace")
    tr = trace.install(path)
    tr.instant("health_phase", phase="degraded", latency_ms=412.0)
    tr.counter("fetch.queue_depth", depth=5)
    trace.uninstall()
    events = trace_report.load_events(path)
    kinds = {e["ph"] for e in events}
    assert "i" in kinds and "C" in kinds
    summary = trace_report.summarize(events)
    assert summary["health_transitions"] == [
        {"phase": "degraded", "latency_ms": 412.0}
    ]


# ---------------------------------------------------------------------------
# size-based rotation (r8): PATH -> PATH.1, stitched reports, dropped count


def test_trace_rotation_keeps_two_segments_and_counts_drops(tmp_path):
    metrics_mod.reset_for_tests()
    path = str(tmp_path / "r.trace")
    # tiny cap: every few spans rotate the file
    tr = trace.install(path, max_bytes=2048)
    for i in range(200):
        with tr.span("featurize", rows=i):
            pass
    trace.uninstall()
    import os

    assert os.path.exists(path) and os.path.exists(path + ".1")
    # both segments bounded by the cap (+ one event of slack)
    assert os.path.getsize(path) <= 2048 + 512
    assert os.path.getsize(path + ".1") <= 2048 + 512
    # rotations beyond the second segment DROP events, loudly counted
    dropped = metrics_mod.get_registry().counter(
        "trace.dropped_events"
    ).snapshot()
    assert dropped > 0
    # each surviving segment is independently a valid trace
    for p in (path + ".1",):
        events = trace_report._load_one(p)
        assert any(e.get("ph") == "X" for e in events)
    # stitched load covers both segments, older first
    stitched = trace_report.load_events(path)
    spans = [e for e in stitched if e.get("ph") == "X"]
    rows = [e["args"]["rows"] for e in spans]
    assert rows == sorted(rows)  # chronological across the stitch
    assert rows[-1] == 199  # the newest event survived
    # accounting: every span not in a surviving segment was counted as
    # dropped (dropped also counts each dead segment's one metadata event)
    assert len(spans) < 200
    assert len(spans) + dropped >= 200
    assert trace_report.main([path]) == 0


def test_trace_unbounded_by_default_never_rotates(tmp_path):
    path = str(tmp_path / "u.trace")
    tr = trace.install(path)  # max_bytes=0
    for _ in range(100):
        with tr.span("parse"):
            pass
    trace.uninstall()
    import os

    assert not os.path.exists(path + ".1")
    assert len(trace_report.load_events(path)) >= 100


# ---------------------------------------------------------------------------
# trace_report as a CHECK (bench scripts gate on its exit status)


def test_trace_report_exit_codes(tmp_path):
    good = tmp_path / "good.trace"
    tr = trace.install(str(good))
    with tr.span("featurize"):
        pass
    trace.uninstall()
    assert trace_report.main([str(good)]) == 0
    assert trace_report.main([str(good), "--json"]) == 0

    bad = tmp_path / "bad.trace"
    bad.write_text("this is { not a trace\n")
    assert trace_report.main([str(bad)]) == 2
    empty = tmp_path / "empty.trace"
    empty.write_text("")
    assert trace_report.main([str(empty)]) == 2
    only_bracket = tmp_path / "brackets.trace"
    only_bracket.write_text("[\n")
    assert trace_report.main([str(only_bracket)]) == 2
    missing = tmp_path / "missing.trace"
    assert trace_report.main([str(missing)]) == 2
    # a JSON document that parses but is not a trace
    scalar = tmp_path / "scalar.trace"
    scalar.write_text("42")
    assert trace_report.main([str(scalar)]) == 2


def test_trace_report_accepts_closed_json_array(tmp_path):
    path = tmp_path / "closed.trace"
    path.write_text(json.dumps([
        {"name": "parse", "ph": "X", "ts": 0, "dur": 1000, "pid": 1,
         "tid": 1, "args": {"bytes": 10}},
    ]))
    summary = trace_report.summarize(trace_report.load_events(str(path)))
    assert summary["stages"]["parse"]["count"] == 1
    assert summary["stages"]["parse"]["bytes"] == 10


# ---------------------------------------------------------------------------
# integration smoke (tier-1, fast): the flagship app under --trace


def _write_replay(tmp_path, n):
    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    path = tmp_path / "tweets.jsonl"
    with open(path, "w") as fh:
        for s in SyntheticSource(
            total=n, seed=7, base_ms=1785320000000
        ).produce():
            fh.write(json.dumps(_status_json(s)) + "\n")
    return path


def _run_linear(tmp_path, extra):
    """Run the flagship app over a 4-batch corpus (to natural exhaustion, so
    the source thread flushes its aggregated parse span), counting every
    jax.device_get — the ONLY host fetch the back-to-back pipeline makes
    (FetchPipeline submits one per batch)."""
    import jax

    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.config import ConfArguments

    jax.devices()  # lock the conftest's backend before local[1]
    tmp_path.mkdir(parents=True, exist_ok=True)
    path = _write_replay(tmp_path, 4 * 16)
    conf = ConfArguments().parse([
        "--source", "replay", "--replayFile", str(path),
        "--seconds", "0", "--backend", "cpu",
        "--batchBucket", "16", "--tokenBucket", "64",
        "--master", "local[1]",
    ] + extra)
    calls = {"n": 0}
    real = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real(x)

    jax.device_get = counting
    try:
        totals = app.run(conf)
    finally:
        jax.device_get = real
    return totals, calls["n"]


def test_trace_smoke_linear_app(tmp_path):
    """Acceptance: a --trace replay run yields a valid trace containing
    every pipeline stage, with per-batch dispatch/fetch spans, and the
    tracing adds no host fetches (fetch count == batches, and == the
    untraced run's count)."""
    metrics_mod.reset_for_tests()
    totals_off, fetches_off = _run_linear(tmp_path / "off", [])
    assert totals_off["batches"] == 4
    assert fetches_off == 4  # FetchPipeline: exactly one fetch per batch

    metrics_mod.reset_for_tests()
    trace_path = tmp_path / "run.trace"
    totals_on, fetches_on = _run_linear(
        tmp_path / "on", ["--trace", str(trace_path)]
    )
    assert totals_on["batches"] == totals_off["batches"]
    # ZERO extra host fetches from instrumentation (measurement integrity)
    assert fetches_on == fetches_off

    # the registry saw the same story
    reg = metrics_mod.get_registry().snapshot()
    assert reg["counters"]["fetch.count"] == 4
    assert reg["counters"]["pipeline.batches"] == 4
    assert reg["counters"]["pipeline.tweets"] == totals_on["count"]
    assert reg["counters"]["wire.bytes"] > 0
    assert reg["histograms"]["fetch.latency_s"]["count"] == 4

    # trace is valid (trace_report exit 0) and carries the stage set
    assert trace_report.main([str(trace_path)]) == 0
    summary = trace_report.summarize(
        trace_report.load_events(str(trace_path))
    )
    stages = set(summary["stages"])
    for stage in ("source_read", "parse", "featurize", "dispatch", "fetch",
                  "stats_publish"):
        assert stage in stages, f"missing stage {stage} in {stages}"
    # per-batch stages traced once per batch
    assert summary["stages"]["dispatch"]["count"] == 4
    assert summary["stages"]["fetch"]["count"] == 4
    # featurize spans carry bytes-on-wire (the bottleneck-ladder input)
    assert summary["stages"]["featurize"]["bytes"] > 0


def test_trace_off_leaves_no_file(tmp_path):
    metrics_mod.reset_for_tests()
    _run_linear(tmp_path, [])
    assert not list(tmp_path.glob("*.trace"))
    assert not trace.get().enabled
