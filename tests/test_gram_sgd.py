"""Differential tests: the Gram-domain (dual) sparse SGD loop (ops/gram.py)
against the per-iteration gather/scatter formulation — the two are the same
recursion in different bases, so multi-step weight trajectories must agree to
float tolerance across every parity-critical semantic: √-decay step sizes,
SquaredL2Updater pre-scale (including entries the batch never touches),
Bernoulli mini-batch sampling, convergence freeze, zero-sample skip, and the
logistic residual. ``gram_matrix`` itself is pinned against the dense
densify-matmul reference, including the cond-gated two-plane split for
counts > 255 and non-integral token values."""

import numpy as np

import jax
import jax.numpy as jnp

from twtml_tpu.features.batch import NUM_NUMBER_FEATURES, FeatureBatch, UnitBatch
from twtml_tpu.models.logistic import StreamingLogisticRegressionWithSGD
from twtml_tpu.models.sgd import make_sgd_train_step, zero_weights
from twtml_tpu.ops.gram import fits_gram, gram_matrix
from twtml_tpu.ops.sparse import densify_text

F_TEXT = 512  # small enough for fast CPU tests; forced sparse via use_sparse


def random_batch(rng, b=24, l=12, f_text=F_TEXT, label_scale=50.0):
    token_idx = rng.integers(0, f_text, size=(b, l)).astype(np.int32)
    token_val = rng.integers(1, 4, size=(b, l)).astype(np.float32)
    # padded token slots: idx 0, val 0 (the batch contract)
    token_val[:, l - 2 :] = 0.0
    token_idx[:, l - 2 :] = 0
    numeric = rng.normal(size=(b, NUM_NUMBER_FEATURES)).astype(np.float32) * 0.1
    label = rng.uniform(0, label_scale, size=(b,)).astype(np.float32)
    mask = np.ones((b,), np.float32)
    mask[b - 3 :] = 0.0  # padding rows
    token_val[b - 3 :] = 0.0
    numeric[b - 3 :] = 0.0
    label[b - 3 :] = 0.0
    return FeatureBatch(token_idx, token_val, numeric, label, mask)


def run_chain(step, batches, w0):
    w = jnp.asarray(w0)
    outs = []
    for b in batches:
        w, out = step(w, b)
        outs.append(out)
    return np.asarray(w), outs


def both_paths(batches, w0, **kw):
    kw.setdefault("num_text_features", F_TEXT)
    kw.setdefault("use_sparse", True)
    kw.setdefault("num_iterations", 25)
    kw.setdefault("step_size", 0.05)
    scatter = make_sgd_train_step(use_gram=False, **kw)
    gram = make_sgd_train_step(use_gram=True, **kw)
    w_s, out_s = run_chain(scatter, batches, w0)
    w_g, out_g = run_chain(gram, batches, w0)
    return (w_s, out_s), (w_g, out_g)


def assert_trajectories_match(res_s, res_g, rtol=2e-4, atol=2e-4):
    (w_s, out_s), (w_g, out_g) = res_s, res_g
    scale = max(1.0, float(np.max(np.abs(w_s))))
    np.testing.assert_allclose(w_g, w_s, rtol=rtol, atol=atol * scale)
    for a, b in zip(out_s, out_g):
        # predictions are pre-update in both paths — identical math
        np.testing.assert_allclose(
            np.asarray(b.predictions), np.asarray(a.predictions), rtol=1e-5, atol=1e-4
        )
        np.testing.assert_allclose(float(b.mse), float(a.mse), rtol=1e-4, atol=1e-3)


def test_gram_matrix_matches_dense_reference():
    rng = np.random.default_rng(0)
    batch = random_batch(rng)
    dense = np.asarray(
        densify_text(jnp.asarray(batch.token_idx), jnp.asarray(batch.token_val), F_TEXT)
    )
    z = np.concatenate([dense, batch.numeric], axis=1)
    ref = z @ z.T
    got = np.asarray(
        gram_matrix(
            jnp.asarray(batch.token_idx),
            jnp.asarray(batch.token_val),
            jnp.asarray(batch.numeric),
            F_TEXT,
        )
    )
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-4)


def test_gram_matrix_two_plane_split_counts_above_255():
    rng = np.random.default_rng(1)
    batch = random_batch(rng)
    token_val = batch.token_val.copy()
    token_idx = batch.token_idx.copy()
    token_idx[0, :5] = 7  # duplicate feature occurrences...
    token_val[0, :5] = 100.0  # ...summing to 500 > 255: bf16-inexact count
    dense = np.asarray(densify_text(jnp.asarray(token_idx), jnp.asarray(token_val), F_TEXT))
    z = np.concatenate([dense, batch.numeric], axis=1)
    ref = z @ z.T
    got = np.asarray(
        gram_matrix(
            jnp.asarray(token_idx),
            jnp.asarray(token_val),
            jnp.asarray(batch.numeric),
            F_TEXT,
        )
    )
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-2)


def test_gram_matrix_int8_plane_is_bit_exact():
    """Row absolute mass ≤ 127 rides the s8×s8→s32 plane: integer
    accumulation end-to-end, so the text block must equal the dense integer
    reference EXACTLY (not allclose — the int8 plane does no rounding)."""
    rng = np.random.default_rng(20)
    batch = random_batch(rng)  # vals in {1,2,3}, L=12 ⇒ mass ≤ 36 ≤ 127
    assert np.all(np.sum(np.abs(batch.token_val), axis=1) <= 127.0)
    dense = np.asarray(
        densify_text(jnp.asarray(batch.token_idx), jnp.asarray(batch.token_val), F_TEXT)
    )
    ref = dense @ dense.T
    from twtml_tpu.ops.gram import text_gram

    got = np.asarray(
        text_gram(jnp.asarray(batch.token_idx), jnp.asarray(batch.token_val), F_TEXT)
    )
    np.testing.assert_array_equal(got, ref)


def test_gram_matrix_int8_gate_mixed_sign_boundary():
    """Mixed-sign rows at the gate edge: absolute mass exactly 127 rides the
    int8 plane (bit-exact, array_equal); mass 128 falls to the bf16 plane
    (still correct — counts here are small, so bf16 is exact too; the test
    that actually DISTINGUISHES the planes at the boundary is
    test_gram_matrix_int8_gate_count_wrap_boundary's sign witness)."""
    from twtml_tpu.ops.gram import text_gram

    for vals, exact in [([60.0, -60.0, 7.0, 0.0], True),
                        ([64.0, -57.0, 7.0, 0.0], False)]:
        token_idx = np.array([[3, 3, 9, 11]], np.int32)
        token_val = np.array([vals], np.float32)
        dense = np.asarray(
            densify_text(jnp.asarray(token_idx), jnp.asarray(token_val), F_TEXT)
        )
        ref = dense @ dense.T
        got = np.asarray(text_gram(jnp.asarray(token_idx), jnp.asarray(token_val), F_TEXT))
        if exact:
            np.testing.assert_array_equal(got, ref)
        else:
            np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-2)


def test_gram_matrix_int8_gate_count_wrap_boundary():
    """A per-feature count at the int8 edge, witnessed through an
    OFF-DIAGONAL entry (squares hide a ±wrap: (−128)² = 128²). Two rows
    share feature 7; row0's count is 127 (int8-exact, must be array-equal)
    or 128 (would wrap to −128 if the gate admitted it — G[0,1] flips sign,
    so a gate loosened to ≤128, or a wrong narrowing dtype, fails here)."""
    from twtml_tpu.ops.gram import text_gram

    for count, exact in [(127.0, True), (128.0, False)]:
        token_idx = np.array([[7, 0], [7, 0]], np.int32)
        token_val = np.array([[count, 0.0], [1.0, 0.0]], np.float32)
        got = np.asarray(
            text_gram(jnp.asarray(token_idx), jnp.asarray(token_val), F_TEXT)
        )
        expected = np.array([[count * count, count], [count, 1.0]], np.float32)
        if exact:
            np.testing.assert_array_equal(got, expected)
        else:
            np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-2)
        assert got[0, 1] > 0.0  # the wrap witness: sign must not flip


def test_gram_matrix_int8_plane_disabled_still_matches():
    """int8_plane=False rebuilds the r3 two-plane program (the bench A/B
    baseline) and stays on the reference."""
    from twtml_tpu.ops.gram import text_gram

    rng = np.random.default_rng(21)
    batch = random_batch(rng)
    dense = np.asarray(
        densify_text(jnp.asarray(batch.token_idx), jnp.asarray(batch.token_val), F_TEXT)
    )
    ref = dense @ dense.T
    got = np.asarray(
        text_gram(
            jnp.asarray(batch.token_idx),
            jnp.asarray(batch.token_val),
            F_TEXT,
            int8_plane=False,
        )
    )
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-4)


def test_gram_matrix_fractional_values():
    rng = np.random.default_rng(2)
    batch = random_batch(rng)
    token_val = batch.token_val * 0.37  # non-integral: one bf16 plane can't hold it
    dense = np.asarray(densify_text(jnp.asarray(batch.token_idx), jnp.asarray(token_val), F_TEXT))
    z = np.concatenate([dense, batch.numeric], axis=1)
    ref = z @ z.T
    got = np.asarray(
        gram_matrix(
            jnp.asarray(batch.token_idx),
            jnp.asarray(token_val),
            jnp.asarray(batch.numeric),
            F_TEXT,
        )
    )
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-2)


def test_multi_batch_trajectory_matches_scatter():
    rng = np.random.default_rng(3)
    batches = [random_batch(rng) for _ in range(4)]
    w0 = zero_weights(F_TEXT)
    res = both_paths(batches, w0)
    assert_trajectories_match(*res)


def test_l2_scales_untouched_weights_identically():
    """W_prev entries the batch never references must shrink by the exact
    per-iteration (1 − η·λ) product — the lazy c-scale of the dual basis
    against the scatter loop's explicit full-vector scaling."""
    rng = np.random.default_rng(4)
    # tokens confined to [0, 64): features ≥ 64 are untouched by every batch
    batches = []
    for _ in range(3):
        b = random_batch(rng)
        batches.append(b._replace(token_idx=(b.token_idx % 64).astype(np.int32)))
    w0 = rng.normal(size=(F_TEXT + NUM_NUMBER_FEATURES,)).astype(np.float32)
    res_s, res_g = both_paths(batches, w0, l2_reg=0.05, convergence_tol=0.0)
    assert_trajectories_match(res_s, res_g)
    # untouched entries did change (the L2 shrink really applied)...
    w_s = res_s[0]
    assert not np.allclose(w_s[64:F_TEXT], w0[64:F_TEXT])
    # ...multiplicatively, by the same factor everywhere
    ratio = w_s[64:F_TEXT] / w0[64:F_TEXT]
    np.testing.assert_allclose(ratio, ratio[0], rtol=1e-5)


def test_mini_batch_sampling_matches():
    rng = np.random.default_rng(5)
    batches = [random_batch(rng) for _ in range(3)]
    res = both_paths(batches, zero_weights(F_TEXT), mini_batch_fraction=0.5)
    assert_trajectories_match(*res)


def test_convergence_freeze_matches():
    """A tight tolerance freezes both formulations at the same iteration;
    trajectories (and therefore the frozen weights) agree."""
    rng = np.random.default_rng(6)
    batches = [random_batch(rng, label_scale=1.0)]
    res = both_paths(
        batches, zero_weights(F_TEXT), convergence_tol=0.05, num_iterations=50
    )
    assert_trajectories_match(*res)


def test_zero_valid_batch_is_identity():
    rng = np.random.default_rng(7)
    b = random_batch(rng)
    empty = b._replace(mask=np.zeros_like(b.mask))
    w0 = rng.normal(size=(F_TEXT + NUM_NUMBER_FEATURES,)).astype(np.float32)
    step = make_sgd_train_step(
        num_text_features=F_TEXT, use_sparse=True, use_gram=True,
        num_iterations=10, step_size=0.05, l2_reg=0.1,
    )
    w1, _ = step(jnp.asarray(w0), empty)
    np.testing.assert_allclose(np.asarray(w1), w0, rtol=1e-6, atol=0)


def test_logistic_residual_matches():
    rng = np.random.default_rng(8)
    batches = []
    for _ in range(3):
        b = random_batch(rng)
        batches.append(b._replace(label=(b.label > 25).astype(np.float32) * b.mask))
    cls = StreamingLogisticRegressionWithSGD
    res = both_paths(
        batches,
        zero_weights(F_TEXT),
        residual_fn=cls.residual_fn,
        prediction_fn=cls.prediction_fn,
        round_predictions=cls.round_predictions,
        step_size=0.5,
    )
    assert_trajectories_match(*res)


def test_unit_batch_rides_gram_path():
    """UnitBatch → on-device hash → Gram loop equals the same UnitBatch
    through the scatter loop (hash runs in both programs identically)."""
    rng = np.random.default_rng(9)
    texts = ["tpu stream %d" % i for i in range(8)]
    units = np.zeros((8, 16), np.uint16)
    length = np.zeros((8,), np.int32)
    for i, t in enumerate(texts):
        enc = np.frombuffer(t.encode("utf-16-le"), np.uint16)
        units[i, : len(enc)] = enc
        length[i] = len(enc)
    batch = UnitBatch(  # jnp arrays: the step runs unjitted in this test
        jnp.asarray(units),
        jnp.asarray(length),
        rng.normal(size=(8, NUM_NUMBER_FEATURES)).astype(np.float32) * 0.1,
        rng.uniform(0, 50, size=(8,)).astype(np.float32),
        np.ones((8,), np.float32),
    )
    res = both_paths([batch], zero_weights(F_TEXT))
    assert_trajectories_match(*res)


def test_gram_matrix_mixed_sign_values_stay_exact():
    """Row-sum cancellation must not fool the bf16-exactness gate: mixed-sign
    integral values whose sum is small but whose per-feature count magnitude
    exceeds 255 must take the exact fallback."""
    token_idx = np.array([[7, 7, 9, 0]], np.int32)
    token_val = np.array([[150.0, 151.0, -200.0, 0.0]], np.float32)
    numeric = np.zeros((1, NUM_NUMBER_FEATURES), np.float32)
    got = np.asarray(
        gram_matrix(
            jnp.asarray(token_idx),
            jnp.asarray(token_val),
            jnp.asarray(numeric),
            F_TEXT,
        )
    )
    # exact: 301² + 200² = 130601
    np.testing.assert_allclose(got[0, 0], 301.0**2 + 200.0**2, rtol=1e-6)


def test_bfloat16_weights_run_the_gram_loop():
    """Explicit use_gram with bf16 weights must trace (type-stable fori_loop
    carry) and track the bf16 scatter path."""
    rng = np.random.default_rng(11)
    batches = [random_batch(rng) for _ in range(2)]
    w0 = zero_weights(F_TEXT, dtype=jnp.bfloat16)
    (w_s, _), (w_g, _) = both_paths(batches, w0)
    np.testing.assert_allclose(
        np.asarray(w_g, np.float32), np.asarray(w_s, np.float32),
        rtol=0.1, atol=0.1,  # bf16 trajectories diverge fast; same ballpark
    )


def test_auto_gate_is_f32_only():
    """The default path must not auto-select Gram for non-f32 weights (the
    bf16-plane G build would silently change f64 semantics)."""
    rng = np.random.default_rng(12)
    b = random_batch(rng)
    step = make_sgd_train_step(
        num_text_features=F_TEXT, use_sparse=True,
        num_iterations=5, step_size=0.05,
    )
    # bf16 weights trace and run through the (auto-selected) scatter loop
    w0 = zero_weights(F_TEXT, dtype=jnp.bfloat16)
    w1, _ = step(jnp.asarray(w0), b)
    assert w1.dtype == jnp.bfloat16


def test_feature_sharded_gram_sampling_matches_single_device():
    """2D (data × model) mesh with fraction < 1: the gram path's one global
    mask must bit-match the single-device gram trajectory."""
    import jax

    from twtml_tpu.parallel import ParallelSGDModel, make_mesh
    from twtml_tpu.parallel.sharding import shard_batch

    rng = np.random.default_rng(15)
    batches = [random_batch(rng, b=32) for _ in range(2)]
    single = make_sgd_train_step(
        num_text_features=F_TEXT, use_sparse=True, use_gram=True,
        num_iterations=20, step_size=0.05, mini_batch_fraction=0.5, l2_reg=0.01,
    )
    w_ref, _ = run_chain(single, batches, zero_weights(F_TEXT))

    mesh = make_mesh(num_data=2, num_model=4)
    model = ParallelSGDModel(
        mesh, num_text_features=F_TEXT, num_iterations=20, step_size=0.05,
        mini_batch_fraction=0.5, l2_reg=0.01, use_gram=True,
    )
    for b in batches:
        model.step(shard_batch(b, mesh))
    np.testing.assert_allclose(model.latest_weights, w_ref, rtol=2e-4, atol=2e-4)


def test_feature_sharded_gram_vs_scatter():
    """Same 2D mesh, gram vs scatter formulations agree (fraction=1 so the
    sampling layouts coincide)."""
    import jax

    from twtml_tpu.parallel import ParallelSGDModel, make_mesh
    from twtml_tpu.parallel.sharding import shard_batch

    rng = np.random.default_rng(16)
    batches = [random_batch(rng, b=32) for _ in range(2)]
    mesh = make_mesh(num_data=2, num_model=4)
    kw = dict(
        num_text_features=F_TEXT, num_iterations=15, step_size=0.05, l2_reg=0.02
    )
    m_gram = ParallelSGDModel(mesh, use_gram=True, **kw)
    m_scat = ParallelSGDModel(mesh, use_gram=False, **kw)
    for b in batches:
        sb = shard_batch(b, mesh)
        og, os_ = m_gram.step(sb), m_scat.step(sb)
        np.testing.assert_allclose(float(og.mse), float(os_.mse), rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(
        m_gram.latest_weights, m_scat.latest_weights, rtol=2e-4, atol=2e-4
    )


def test_full_scale_2e18_gram_matches_scatter():
    """Both formulations at the REAL feature width (2^18) through the
    default wire format (units → device hash): the full-scale shapes the
    bench runs, pinned to each other."""
    from twtml_tpu.features.featurizer import Featurizer
    from twtml_tpu.streaming.sources import SyntheticSource

    statuses = list(
        SyntheticSource(total=48, seed=3, base_ms=1785320000000).produce()
    )
    feat = Featurizer(num_text_features=2**18, now_ms=1785320000000)
    batch = feat.featurize_batch_units(statuses, row_bucket=48, pre_filtered=True)
    kw = dict(
        num_text_features=2**18, num_iterations=5, step_size=0.005, l2_reg=0.1
    )
    w0 = zero_weights(2**18)
    scatter = make_sgd_train_step(use_gram=False, **kw)
    gram = make_sgd_train_step(use_gram=True, **kw)
    w_s, out_s = jax.jit(scatter)(w0, batch)
    w_g, out_g = jax.jit(gram)(w0, batch)
    assert float(out_g.mse) == float(out_s.mse)
    np.testing.assert_allclose(np.asarray(w_g), np.asarray(w_s), rtol=1e-4, atol=1e-7)


def test_randomized_config_sweep_matches_scatter():
    """Property-style sweep: random knob combinations (step size, L2,
    sampling fraction, convergence tol, iterations, batch/token shapes,
    value ranges) — every one must keep the two formulations together.
    Seeded, so a failure names its config and reproduces exactly."""
    rng = np.random.default_rng(2026)
    for trial in range(6):
        knobs = dict(
            num_iterations=int(rng.integers(4, 30)),
            step_size=float(rng.choice([0.005, 0.05, 0.2])),
            l2_reg=float(rng.choice([0.0, 0.01, 0.1])),
            mini_batch_fraction=float(rng.choice([1.0, 0.7, 0.4])),
            convergence_tol=float(rng.choice([0.0, 0.001, 0.05])),
        )
        b = int(rng.integers(8, 40))
        l = int(rng.integers(4, 20))
        batches = [
            random_batch(rng, b=b, l=l, label_scale=float(rng.choice([5.0, 500.0])))
            for _ in range(2)
        ]
        w0 = (rng.normal(size=(F_TEXT + NUM_NUMBER_FEATURES,)) * 0.1).astype(
            np.float32
        )
        try:
            res = both_paths(batches, w0, **knobs)
            assert_trajectories_match(*res)
        except AssertionError as exc:  # name the failing config
            raise AssertionError(f"trial {trial} knobs={knobs} b={b} l={l}: {exc}")


def test_auto_gate_picks_gram_only_when_it_fits():
    assert fits_gram(2048, 2**18, 50)
    assert not fits_gram(2048, 2**18, 2)  # too few iterations to amortize
    assert not fits_gram(1 << 20, 2**18, 50)  # dense counts exceed HBM budget


def test_data_axis_gram_matches_single_device():
    """Row-sharded Gram (all-gathered batch, sharded G row panels, replicated
    dual loop) must reproduce the single-device trajectory: same global
    batch, same unfolded sampling key — the collectives are the only
    difference."""
    import jax

    from twtml_tpu.parallel import ParallelSGDModel, make_mesh
    from twtml_tpu.parallel.sharding import shard_batch

    rng = np.random.default_rng(13)
    batches = [random_batch(rng, b=32) for _ in range(3)]

    single = make_sgd_train_step(
        num_text_features=F_TEXT, use_sparse=True, use_gram=True,
        num_iterations=25, step_size=0.05, l2_reg=0.01,
    )
    w_ref, outs_ref = run_chain(single, batches, zero_weights(F_TEXT))

    mesh = make_mesh(num_data=4, devices=jax.devices()[:4])
    model = ParallelSGDModel(
        mesh, num_text_features=F_TEXT, num_iterations=25,
        step_size=0.05, l2_reg=0.01, use_sparse=True,
    )
    outs = [model.step(shard_batch(b, mesh)) for b in batches]
    np.testing.assert_allclose(
        model.latest_weights, w_ref, rtol=2e-4, atol=2e-4
    )
    for a, b in zip(outs_ref, outs):
        np.testing.assert_allclose(float(b.mse), float(a.mse), rtol=1e-4, atol=1e-3)


def test_data_axis_gram_sampling_matches_single_device():
    """fraction < 1: the gram data-axis path draws ONE global mask with the
    unfolded key, so it must bit-match the single-device gram trajectory
    (the scatter loop's per-shard folded keys only match statistically)."""
    import jax

    from twtml_tpu.parallel import ParallelSGDModel, make_mesh
    from twtml_tpu.parallel.sharding import shard_batch

    rng = np.random.default_rng(14)
    batches = [random_batch(rng, b=32) for _ in range(2)]
    single = make_sgd_train_step(
        num_text_features=F_TEXT, use_sparse=True, use_gram=True,
        num_iterations=20, step_size=0.05, mini_batch_fraction=0.5,
    )
    w_ref, _ = run_chain(single, batches, zero_weights(F_TEXT))

    mesh = make_mesh(num_data=4, devices=jax.devices()[:4])
    model = ParallelSGDModel(
        mesh, num_text_features=F_TEXT, num_iterations=20,
        step_size=0.05, mini_batch_fraction=0.5, use_sparse=True,
    )
    for b in batches:
        model.step(shard_batch(b, mesh))
    np.testing.assert_allclose(model.latest_weights, w_ref, rtol=2e-4, atol=2e-4)
