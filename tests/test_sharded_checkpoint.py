"""Checkpoint round-trips on sharded layouts (VERDICT r1 #7).

The reference never checkpoints weights at all (SURVEY.md §5.4); this
framework does, and the state must survive LAYOUT changes: a checkpoint
written from a 2D feature-sharded mesh restores into the same mesh, a
different mesh, or a single device, and training continues exactly where it
left off (the .npz holds the gathered host array; each model re-shards via
set_initial_weights)."""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from twtml_tpu.checkpoint import Checkpointer
from twtml_tpu.features.batch import FeatureBatch
from twtml_tpu.models import StreamingLinearRegressionWithSGD
from twtml_tpu.parallel import ParallelSGDModel, make_mesh

RNG = np.random.default_rng(11)
F_TEXT = 2**18


def make_batch(n=28, pad_to=32, tokens=12, seed=0):
    rng = np.random.default_rng(seed)
    token_idx = rng.integers(0, F_TEXT, size=(pad_to, tokens)).astype(np.int32)
    token_val = rng.integers(1, 3, size=(pad_to, tokens)).astype(np.float32)
    numeric = rng.normal(size=(pad_to, 4)).astype(np.float32) * 0.1
    label = rng.uniform(50, 900, size=(pad_to,)).astype(np.float32)
    mask = np.zeros((pad_to,), dtype=np.float32)
    mask[:n] = 1.0
    token_idx[n:] = 0
    token_val[n:] = 0
    numeric[n:] = 0
    label[n:] = 0
    return FeatureBatch(token_idx, token_val, numeric, label, mask)


BATCHES = [make_batch(seed=s) for s in range(3)]


def model_2d():
    mesh = make_mesh(num_data=4, num_model=2)
    return ParallelSGDModel(
        mesh, num_text_features=F_TEXT, num_iterations=5, step_size=0.005
    )


@pytest.fixture(scope="module")
def uninterrupted():
    """Ground truth: 3 batches straight through on the 2D mesh at 2^18."""
    model = model_2d()
    outs = [model.step(b) for b in BATCHES]
    return model.latest_weights, [float(o.mse) for o in outs]


def test_resume_2e18_on_8_device_mesh(tmp_path, uninterrupted):
    """Save mid-stream from the feature-sharded layout, restore into a FRESH
    2D-mesh model, continue — bit-compatible with never having stopped."""
    w_truth, mse_truth = uninterrupted

    model = model_2d()
    ckpt = Checkpointer(str(tmp_path))
    for i, b in enumerate(BATCHES[:2]):
        model.step(b)
    ckpt.save(2, model.latest_weights, {"count": 56, "batches": 2})

    resumed = model_2d()
    weights, meta = ckpt.restore()
    assert meta["batches"] == 2
    resumed.set_initial_weights(weights)
    # restored text weights live sharded over 'model', not replicated
    text = resumed._weights["text"]
    assert text.sharding.spec == P("model")
    assert text.shape == (F_TEXT,)

    out = resumed.step(BATCHES[2])
    assert float(out.mse) == pytest.approx(mse_truth[2], rel=1e-6)
    np.testing.assert_allclose(resumed.latest_weights, w_truth, rtol=1e-5, atol=1e-8)


def test_checkpoint_portability_across_layouts(tmp_path, uninterrupted):
    """The same checkpoint restores into a 1D data-parallel mesh AND a single
    device, and the continued trajectories agree with the 2D ground truth —
    layout is an execution detail, not part of the saved state."""
    w_truth, _ = uninterrupted

    donor = model_2d()
    for b in BATCHES[:2]:
        donor.step(b)
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(2, donor.latest_weights, {"batches": 2})
    weights, _ = ckpt.restore()

    mesh1d = make_mesh(num_data=8)
    par1d = ParallelSGDModel(
        mesh1d, num_text_features=F_TEXT, num_iterations=5, step_size=0.005
    ).set_initial_weights(weights)
    par1d.step(BATCHES[2])
    np.testing.assert_allclose(par1d.latest_weights, w_truth, rtol=1e-5, atol=1e-8)

    single = StreamingLinearRegressionWithSGD(
        num_text_features=F_TEXT, num_iterations=5, step_size=0.005
    ).set_initial_weights(weights)
    single.step(BATCHES[2])
    np.testing.assert_allclose(single.latest_weights, w_truth, rtol=1e-5, atol=1e-8)


def test_linear_app_resumes_sharded(tmp_path, capsys):
    """CLI-level resume on a sharded model: --master local[4] + checkpoint
    flags, run twice over the replay fixture — the second run is an r21
    exact resume (auto-on journal fast-forwards the covered corpus)."""
    import os

    from twtml_tpu.apps.linear_regression import run
    from twtml_tpu.config import ConfArguments

    data = os.path.join(os.path.dirname(__file__), "data", "tweets.jsonl")

    def conf():
        return ConfArguments().parse([
            "--source", "replay", "--replayFile", data,
            "--seconds", "1", "--backend", "cpu", "--master", "local[4]",
            "--checkpointDir", str(tmp_path), "--checkpointEvery", "1",
            "--lightning", "http://127.0.0.1:9",
            "--twtweb", "http://127.0.0.1:9",
        ])

    first = run(conf())
    assert first["count"] == 6
    second = run(conf())
    assert second["count"] == 6
    assert "count: 6" in capsys.readouterr().out
