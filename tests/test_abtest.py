"""Champion/challenger serving (ISSUE 11): promotion rule, mirror parity,
atomic champion swap, shadow scoring, and the --abtest entry-point face.

The laws under test:

- **mirror parity**: with champion c, every live prediction BIT-equals what
  tenant c's standalone single model would produce for the same batch (the
  PR 9 read-path parity law applied per variant, all rows answered by one
  tenant — never mixed);
- **one gate**: auto-promotion goes through ``serving.snapshot
  .is_promotable`` — an alert-stamped challenger with the best online loss
  is REFUSED and counted, and promotion fires exactly once per stamped
  step;
- **zero added fetches**: challengers ride the champion's coalesced batch
  through the one mirrored program — one ``device_get`` per predict batch,
  shadow scores included.
"""

import threading
import time

import numpy as np
import pytest

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from twtml_tpu.config import ConfArguments  # noqa: E402
from twtml_tpu.features.featurizer import Featurizer  # noqa: E402
from twtml_tpu.models import (  # noqa: E402
    StreamingLinearRegressionWithSGD,
)
from twtml_tpu.serving.abtest import (  # noqa: E402
    ChampionEngine,
    ChampionSelector,
)
from twtml_tpu.serving.plane import ServingPlane  # noqa: E402
from twtml_tpu.serving.snapshot import ServingSnapshot  # noqa: E402
from twtml_tpu.streaming.sources import SyntheticSource  # noqa: E402
from twtml_tpu.telemetry import metrics as _metrics  # noqa: E402

NOW_MS = 1785320000000


@pytest.fixture(autouse=True)
def _clean():
    _metrics.reset_for_tests()
    yield
    _metrics.reset_for_tests()


def _statuses(n, seed=3):
    return list(SyntheticSource(total=n, seed=seed).produce())


def _feat():
    return Featurizer(now_ms=NOW_MS)


def _stamps(entries):
    """meta with per-tenant quality stamps; entries = [(level, loss), ...]"""
    return {"quality": {"level": "ok", "tenants": [
        {"tenant": i, "level": level, "loss": loss,
         "drift_score": 9.0 if level == "alert" else 0.5,
         "loss_trend": 0.0}
        for i, (level, loss) in enumerate(entries)
    ]}}


def _stack(m, seed=0, scale=1e-3):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((m, 1004)) * scale).astype(np.float32)


def _plane(snapshot, engine, **kw):
    kw.setdefault("featurizer", _feat())
    kw.setdefault("batch_rows", 32)
    kw.setdefault("max_wait_ms", 5.0)
    kw.setdefault("depth", 4)
    return ServingPlane(snapshot, engine=engine, **kw)


def _refs_per_tenant(stack, statuses, row_bucket=32):
    """tenant -> the standalone single model's masked predictions."""
    import jax

    batch = _feat().featurize_batch_ragged(
        statuses, row_bucket=row_bucket, pre_filtered=True
    )
    mask = np.asarray(batch.mask) > 0
    refs = {}
    for m in range(stack.shape[0]):
        model = StreamingLinearRegressionWithSGD().set_initial_weights(
            stack[m]
        )
        refs[m] = np.asarray(
            jax.device_get(model.step(batch)).predictions
        )[mask]
    return refs


# ---------------------------------------------------------------------------
# the promotion rule (pure host logic, no jax)

def test_selector_promotes_strictly_better_exactly_once():
    sel = ChampionSelector(3, champion=0)
    meta = _stamps([("ok", 10.0), ("ok", 5.0), ("ok", 7.0)])
    assert sel.consider(meta, step=1) == 1  # best loss wins
    assert sel.champion == 1
    assert _metrics.get_registry().counter(
        "abtest.promotions").snapshot() == 1
    # the same stamped step never fires twice
    assert sel.consider(meta, step=1) is None
    # a step where the champion is already best: no swap
    assert sel.consider(
        _stamps([("ok", 10.0), ("ok", 5.0), ("ok", 7.0)]), step=2
    ) is None
    assert _metrics.get_registry().counter(
        "abtest.promotions").snapshot() == 1


def test_selector_refuses_alert_challenger_through_the_gate():
    """An alert-stamped challenger with the BEST online loss must be
    refused by is_promotable (the one gate) and counted — not silently
    out-ordered; a healthy runner-up still promotes."""
    sel = ChampionSelector(3, champion=0)
    meta = _stamps([("ok", 10.0), ("ok", 5.0), ("alert", 1.0)])
    assert sel.consider(meta, step=4) == 1  # alert refused; ok runner-up
    assert sel.champion == 1
    reg = _metrics.get_registry()
    assert reg.counter("abtest.promotions_refused").snapshot() == 1
    assert reg.counter("abtest.promotions").snapshot() == 1

    # alert-only challenger: refused, champion HOLDS
    sel2 = ChampionSelector(2, champion=0)
    meta2 = _stamps([("ok", 10.0), ("alert", 1.0)])
    assert sel2.consider(meta2, step=1) is None
    assert sel2.champion == 0
    assert reg.counter("abtest.promotions_refused").snapshot() == 2


def test_selector_warn_serves_and_missing_stamps_never_promote():
    sel = ChampionSelector(2, champion=0)
    # warn is a servable level (the PR 8 ladder): it may promote
    assert sel.consider(
        _stamps([("ok", 10.0), ("warn", 2.0)]), step=1
    ) == 1
    # no per-tenant stamps at all: nothing to compare
    sel2 = ChampionSelector(2, champion=0)
    assert sel2.consider({"quality": {"level": "ok"}}, step=1) is None
    assert sel2.consider(None, step=2) is None
    # a challenger without a loss value scores worst: no evidence never
    # promotes
    sel3 = ChampionSelector(2, champion=0)
    meta = {"quality": {"tenants": [
        {"tenant": 0, "level": "ok", "loss": 3.0},
        {"tenant": 1, "level": "ok"},
    ]}}
    assert sel3.consider(meta, step=1) is None


# ---------------------------------------------------------------------------
# mirror parity + zero added fetches

def test_champion_answers_bit_equal_and_one_fetch_per_batch():
    import jax

    stack = _stack(3, seed=7)
    snap = ServingSnapshot(
        step=1, weights=stack,
        meta=_stamps([("ok", 1.0), ("ok", 5.0), ("ok", 9.0)]),
    )
    engine = ChampionEngine(num_text_features=1000, num_tenants=3)
    plane = _plane(snap, engine)
    statuses = _statuses(24, seed=5)
    refs = _refs_per_tenant(stack, statuses)

    calls = {"n": 0}
    real_get = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real_get(x)

    jax.device_get = counting
    try:
        plane.start()
        res = plane.submit(statuses).result(timeout=240)
    finally:
        jax.device_get = real_get
        plane.stop()
    assert engine.champion == 0
    got = np.asarray(res["predictions"], np.float32)
    # THE parity law per variant: all 24 rows are EXACTLY tenant 0's
    # standalone predictions — the mirror answered with one tenant
    assert np.array_equal(refs[0], got)
    # challengers rode the same dispatch: ONE fetch for the whole batch,
    # shadow scores included
    assert calls["n"] == 1

    view = plane.stats()
    assert view["champion"] == 0
    shadows = {s["tenant"]: s for s in view["shadows"]}
    assert shadows[0]["live"] and shadows[0]["liveRows"] == 24
    assert not shadows[1]["live"] and shadows[1]["shadowRows"] == 24
    assert shadows[2]["shadowRows"] == 24
    # live rows land on the champion tile only
    rows = {t["tenant"]: t["rows"] for t in view["tenants"]}
    assert rows == {0: 24, 1: 0, 2: 0}


def test_shadow_divergence_tracks_disagreeing_challenger():
    stack = _stack(2, seed=3, scale=0.5)  # big weights: predictions differ
    snap = ServingSnapshot(
        step=1, weights=stack, meta=_stamps([("ok", 1.0), ("ok", 2.0)]),
    )
    engine = ChampionEngine(num_text_features=1000, num_tenants=2)
    plane = _plane(snap, engine).start()
    try:
        plane.submit(_statuses(24, seed=9)).result(timeout=240)
        view = plane.stats()
    finally:
        plane.stop()
    shadow = [s for s in view["shadows"] if s["tenant"] == 1][0]
    assert shadow["shadowRows"] == 24
    assert shadow["divergence"] > 0.0


# ---------------------------------------------------------------------------
# the champion-swap differential (the ISSUE 11 satellite)

def test_champion_swap_is_atomic_and_fires_once_under_load():
    """Differential: a new snapshot whose stamps favor the challenger flips
    the champion pointer EXACTLY once; under concurrent load every response
    bit-matches ONE tenant of its claimed snapshot (never a mixed batch),
    and an alert-stamped best-loss challenger is refused and counted."""
    stack = _stack(3, seed=11, scale=0.05)
    statuses = _statuses(8, seed=21)
    refs = _refs_per_tenant(stack, statuses)

    # step 1: champion 0 best; the alert tenant 2 has the best loss and
    # must be refused through is_promotable (counted below)
    snap1 = ServingSnapshot(
        step=1, weights=stack,
        meta=_stamps([("ok", 1.0), ("ok", 5.0), ("alert", 0.1)]),
    )
    engine = ChampionEngine(num_text_features=1000, num_tenants=3)
    plane = _plane(snap1, engine, max_wait_ms=0.5).start()
    plane.warmup()
    assert engine.champion == 0

    results = []
    errors = []

    def loader():
        try:
            for _ in range(10):
                results.append(
                    plane.submit(list(statuses)).result(timeout=120)
                )
        except Exception as exc:  # pragma: no cover - failure evidence
            errors.append(exc)

    threads = [threading.Thread(target=loader) for _ in range(3)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.1)
        # step 2: challenger 1 now strictly better; tenant 2 still alert
        plane.hot_swap(ServingSnapshot(
            step=2, weights=stack,
            meta=_stamps([("ok", 1.0), ("ok", 0.5), ("alert", 0.1)]),
        ))
        for t in threads:
            t.join(timeout=180)
    finally:
        plane.stop()
    assert not errors
    assert len(results) == 30
    assert engine.champion == 1  # the pointer flipped...
    reg = _metrics.get_registry()
    assert reg.counter("abtest.promotions").snapshot() == 1  # ...once
    # the alert challenger was refused at BOTH stamped steps, via the gate
    assert reg.counter("abtest.promotions_refused").snapshot() == 2

    champion_by_step = {1: 0, 2: 1}
    seen_steps = set()
    for res in results:
        step = res["snapshot_step"]
        seen_steps.add(step)
        champ = champion_by_step[step]
        # dispatch-time (snapshot, champion) ride together: the response
        # must be EXACTLY that tenant's vector — a torn swap would match
        # neither, a mixed batch would match no single tenant
        assert np.array_equal(
            refs[champ], np.asarray(res["predictions"], np.float32)
        ), f"response torn across tenants (claimed step {step})"
    assert 2 in seen_steps  # the promoted champion actually served traffic


# ---------------------------------------------------------------------------
# the --abtest entry-point face

def _save_stacked_ckpt(directory, step, weights, entries):
    from twtml_tpu.checkpoint import Checkpointer

    meta = {"count": step * 10, "batches": step}
    meta["quality"] = _stamps(entries)["quality"]
    return Checkpointer(str(directory)).save(
        step, np.asarray(weights, np.float32), meta
    )


def test_serve_app_abtest_end_to_end(tmp_path, monkeypatch):
    """Boot apps.serve --abtest on over a stamped tenant-stack checkpoint:
    the champion answers over real HTTP, the Serving view carries the
    champion + shadow tiles, and a single-model checkpoint is refused."""
    import jax

    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    from twtml_tpu.apps import serve as serve_app
    from twtml_tpu.serving.client import ServingClient

    stack = _stack(2, seed=2)
    ck = tmp_path / "ck"
    _save_stacked_ckpt(ck, 3, stack, [("ok", 5.0), ("ok", 9.0)])

    stop = threading.Event()
    ready = {}
    ready_evt = threading.Event()

    def started(server, plane, promoter):
        ready["port"] = server._runner.addresses[0][1]
        ready_evt.set()

    conf = ConfArguments().parse([
        "--backend", "cpu", "--master", "local[1]",
        "--checkpointDir", str(ck), "--servePort", "0",
        "--serveBatchRows", "32", "--serveMaxWaitMs", "2",
        "--servePromoteEvery", "600", "--abtest", "on",
    ])
    result = {}

    def runner():
        result["stats"] = serve_app.run(conf, started=started,
                                        stop_event=stop)

    thread = threading.Thread(target=runner)
    thread.start()
    try:
        assert ready_evt.wait(timeout=300), "serve app never came up"
        statuses = _statuses(6, seed=2)
        rows = [{
            "text": s.retweeted_status.text,
            "followers_count": s.retweeted_status.followers_count,
            "favourites_count": s.retweeted_status.favourites_count,
            "friends_count": s.retweeted_status.friends_count,
            "created_at_ms": s.retweeted_status.created_at_ms,
        } for s in statuses]
        res = ServingClient(f"http://127.0.0.1:{ready['port']}").predict(rows)
        assert res["snapshotStep"] == 3 and res["servedRows"] == 6
    finally:
        stop.set()
        thread.join(timeout=120)
    assert not thread.is_alive()
    assert result["stats"]["champion"] == 0
    assert [s["tenant"] for s in result["stats"]["shadows"]] == [0, 1]

    # parity through the full HTTP + JSON + mirrored-plane stack
    batch = _feat().featurize_batch_ragged(
        statuses, row_bucket=32, pre_filtered=True
    )
    ref_model = StreamingLinearRegressionWithSGD().set_initial_weights(
        stack[0]
    )
    ref = np.asarray(jax.device_get(ref_model.step(batch)).predictions)[
        np.asarray(batch.mask) > 0
    ]
    assert np.array_equal(ref, np.asarray(res["predictions"], np.float32))


def test_serve_app_abtest_refuses_single_model_checkpoint(tmp_path):
    from twtml_tpu.apps import serve as serve_app
    from twtml_tpu.checkpoint import Checkpointer

    ck = tmp_path / "ck"
    Checkpointer(str(ck)).save(
        1, np.zeros(1004, np.float32), {"count": 1, "batches": 1}
    )
    conf = ConfArguments().parse([
        "--backend", "cpu", "--checkpointDir", str(ck), "--abtest", "on",
    ])
    with pytest.raises(SystemExit, match="tenant-stack"):
        serve_app.run(conf)


def test_per_tenant_quality_stamps_ride_the_checkpoint_meta():
    """The trainer-side half of the A/B loop: the modelwatch checkpoint
    stamp grows per-tenant entries (level/drift/trend/loss) on the tenant
    plane — the online score the promotion rule compares."""
    from twtml_tpu.telemetry import modelwatch

    modelwatch.reset_for_tests()
    try:
        from twtml_tpu.ops.quality import QUALITY_WIDTH

        q = np.zeros((2, QUALITY_WIDTH), np.float64)
        modelwatch.record_tick(q, np.array([8.0, 8.0]), np.array([4.0, 2.0]))
        stamp = modelwatch.snapshot_for_checkpoint()
        assert stamp is not None and len(stamp["tenants"]) == 2
        t1 = stamp["tenants"][1]
        assert t1["tenant"] == 1 and t1["level"] == "ok"
        assert t1["loss"] == pytest.approx(2.0)
        # the stamp is what the selector consumes end to end
        sel = ChampionSelector(2, champion=0)
        assert sel.consider({"quality": stamp}, step=1) == 1
    finally:
        modelwatch.reset_for_tests()
