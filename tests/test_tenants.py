"""Multi-tenant model plane (ISSUE 7): M models, one program, one fetch.

The law under test is threefold:
- **M=1 bit-parity**: the tenant-stacked program produces byte-identical
  weights AND stats to the existing single-tenant program, across the
  stacked and coalesced (group) tenant wires and the ragged wire — the
  parity law applied to the new plane;
- **per-tenant parity**: at M>1 every tenant's trajectory bit-equals a
  separate single-tenant model trained on its routed sub-stream (routing
  moves rows, never semantics);
- **one fetch per tick**: a real M=8 app run makes exactly ONE
  ``jax.device_get`` per dispatched batch — the PR 1/5 counting idiom on
  the new plane (fetch amortization is the whole point, the r2 law).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from twtml_tpu.config import ConfArguments  # noqa: E402
from twtml_tpu.features.batch import (  # noqa: E402
    RaggedUnitBatch,
    split_batch_tenants,
    tenant_route_keys,
    tenant_rows,
)
from twtml_tpu.features.featurizer import Featurizer  # noqa: E402
from twtml_tpu.models import (  # noqa: E402
    StreamingLinearRegressionWithSGD,
    StreamingLogisticRegressionWithSGD,
)
from twtml_tpu.parallel import TenantStackModel  # noqa: E402
from twtml_tpu.parallel.tenants import (  # noqa: E402
    aggregate_tenant_output,
    split_tenant_output,
)
from twtml_tpu.streaming.sources import SyntheticSource  # noqa: E402
from twtml_tpu.telemetry import metrics as _metrics  # noqa: E402
from twtml_tpu.telemetry import tenants as _tenants_tel  # noqa: E402

NOW_MS = 1785320000000


@pytest.fixture(autouse=True)
def _fresh_registries():
    _metrics.reset_for_tests()
    _tenants_tel.reset_for_tests()
    yield
    _metrics.reset_for_tests()
    _tenants_tel.reset_for_tests()


def _ragged_batches(n=512, b=256, seed=3, unicode_mix=False):
    feat = Featurizer(now_ms=NOW_MS)
    statuses = list(SyntheticSource(total=n, seed=seed).produce())
    if unicode_mix:
        import dataclasses

        for i, s in enumerate(statuses):
            if i % 3 == 0:
                o = s.retweeted_status
                statuses[i] = dataclasses.replace(
                    s,
                    retweeted_status=dataclasses.replace(
                        o, text=o.text + " café 中文"
                    ),
                )
    return [
        feat.featurize_batch_ragged(
            statuses[i : i + b], row_bucket=b, pre_filtered=True
        )
        for i in range(0, n, b)
    ]


def _unit_batches(n=512, b=256, seed=3):
    feat = Featurizer(now_ms=NOW_MS)
    statuses = list(SyntheticSource(total=n, seed=seed).produce())
    return [
        feat.featurize_batch_units(
            statuses[i : i + b], row_bucket=b, pre_filtered=True
        )
        for i in range(0, n, b)
    ]


# ---------------------------------------------------------------------------
# routing


def test_route_keys_deterministic_and_in_range():
    rb = _ragged_batches()[0]
    ids1 = tenant_route_keys(rb, 8)
    ids2 = tenant_route_keys(rb, 8)
    assert np.array_equal(ids1, ids2)
    assert ids1.shape == (rb.mask.shape[0],)
    assert ids1.min() >= 0 and ids1.max() < 8


def test_split_conserves_rows_and_order():
    """Every valid row lands in exactly one tenant, original relative order
    preserved per tenant, padded shape shared — the row-conservation
    invariant the CI smoke asserts end-to-end."""
    rb = _ragged_batches()[0]
    ids = tenant_route_keys(rb, 4)
    parts = split_batch_tenants(rb, ids, 4)
    valid = int(np.asarray(rb.mask).sum())
    assert sum(int(np.asarray(p.mask).sum()) for p in parts) == valid
    offs = np.asarray(rb.offsets, np.int64)
    units = np.asarray(rb.units)
    for m, (rows, part) in enumerate(zip(tenant_rows(rb, ids, 4), parts)):
        # same signature: shapes, dtype, row_len all match the parent
        assert part.units.shape == rb.units.shape
        assert part.units.dtype == rb.units.dtype
        assert part.row_len == rb.row_len
        assert np.all(np.diff(rows) > 0)  # ascending = order preserved
        p_offs = np.asarray(part.offsets, np.int64)
        for j, r in enumerate(rows):
            got = np.asarray(part.units)[p_offs[j] : p_offs[j + 1]]
            want = units[offs[r] : offs[r + 1]]
            assert np.array_equal(got, want), (m, j, r)
            assert float(part.label[j]) == float(rb.label[r])
            assert np.array_equal(part.numeric[j], rb.numeric[r])


def test_split_dry_tenant_is_all_padding():
    rb = _ragged_batches()[0]
    ids = np.zeros(rb.mask.shape[0], np.int32)  # everything to tenant 0
    parts = split_batch_tenants(rb, ids, 3)
    for p in parts[1:]:
        assert int(np.asarray(p.mask).sum()) == 0
        assert int(np.asarray(p.offsets)[-1]) == 0
    # tenant 0 gets the batch back byte-identically (order + same buckets)
    assert np.array_equal(parts[0].units, rb.units)
    assert np.array_equal(parts[0].offsets, rb.offsets)
    assert np.array_equal(parts[0].label, rb.label)


def test_lang_key_separates_scripts():
    rb = _ragged_batches(unicode_mix=True)[0]
    ids = tenant_route_keys(rb, 4, mode="lang")
    valid = np.asarray(rb.mask) > 0
    # the synthetic mix has both pure-ASCII and wide rows → >1 class
    assert len(set(ids[valid].tolist())) > 1


def test_lang_key_rejects_host_hash_wire():
    feat = Featurizer(now_ms=NOW_MS)
    statuses = list(SyntheticSource(total=64, seed=3).produce())
    fb = feat.featurize_batch(statuses, row_bucket=64, pre_filtered=True)
    with pytest.raises(ValueError, match="lang"):
        tenant_route_keys(fb, 4, mode="lang")


# ---------------------------------------------------------------------------
# M=1 bit-parity (acceptance criterion)


@pytest.mark.parametrize("wire_pack", ["stacked", "group"])
def test_m1_bit_parity_ragged(wire_pack):
    """The M=1 tenant-stacked program bit-equals the existing single-tenant
    program — weights AND per-batch stats — on the ragged wire, for both
    tenant-wire layouts."""
    single = StreamingLinearRegressionWithSGD()
    mt = TenantStackModel(
        1, step_size=single.default_step_size, wire_pack=wire_pack
    )
    for rb in _ragged_batches(unicode_mix=True):
        o1 = single.step(rb)
        o2 = mt.step(rb)
        for f in ("count", "mse", "real_stdev", "pred_stdev"):
            assert np.asarray(getattr(o1, f)).tobytes() == (
                np.asarray(getattr(o2, f))[0].tobytes()
            ), f
        assert np.array_equal(
            np.asarray(o1.predictions), np.asarray(o2.predictions)[0]
        )
    assert single.latest_weights.tobytes() == (
        mt.latest_weights[0].tobytes()
    )


def test_m1_bit_parity_padded_units_wire():
    single = StreamingLinearRegressionWithSGD()
    mt = TenantStackModel(1, step_size=single.default_step_size)
    for ub in _unit_batches():
        o1, o2 = single.step(ub), mt.step(ub)
        assert float(o1.mse) == float(o2.mse[0])
    assert single.latest_weights.tobytes() == mt.latest_weights[0].tobytes()


def test_m1_aggregate_output_is_passthrough():
    single = StreamingLinearRegressionWithSGD()
    mt = TenantStackModel(1, step_size=single.default_step_size)
    rb = _ragged_batches()[0]
    o1 = single.step(rb)
    import jax

    agg = aggregate_tenant_output(jax.device_get(mt.step(rb)), rb, mt)
    assert np.asarray(agg.mse).tobytes() == np.asarray(o1.mse).tobytes()
    assert np.array_equal(np.asarray(agg.predictions), np.asarray(o1.predictions))


# ---------------------------------------------------------------------------
# M>1: per-tenant parity, hyperparams, logistic residual


def test_m4_each_tenant_bit_equals_separate_model():
    """Routing moves rows, never semantics: tenant m's trajectory equals a
    standalone single-tenant model stepped on the routed sub-batches."""
    m = 4
    mt = TenantStackModel(m, step_size=0.1)
    singles = [StreamingLinearRegressionWithSGD(step_size=0.1) for _ in range(m)]
    for rb in _ragged_batches(unicode_mix=True):
        parts = split_batch_tenants(rb, tenant_route_keys(rb, m), m)
        out = mt.step(rb)
        for i in range(m):
            oi = singles[i].step(parts[i])
            assert float(oi.mse) == float(out.mse[i]), i
            assert float(oi.count) == float(out.count[i]), i
    for i in range(m):
        assert singles[i].latest_weights.tobytes() == (
            mt.latest_weights[i].tobytes()
        ), i


def test_per_tenant_hyperparams_are_mapped_leaves():
    """Per-tenant step sizes: tenant i bit-equals a single model built with
    THAT step size on the same routed rows."""
    m = 2
    mt = TenantStackModel(m, step_sizes=[0.05, 0.2])
    singles = [
        StreamingLinearRegressionWithSGD(step_size=s) for s in (0.05, 0.2)
    ]
    for rb in _ragged_batches():
        parts = split_batch_tenants(rb, tenant_route_keys(rb, m), m)
        mt.step(rb)
        for i in range(m):
            singles[i].step(parts[i])
    for i in range(m):
        assert singles[i].latest_weights.tobytes() == (
            mt.latest_weights[i].tobytes()
        ), i


def test_logistic_residual_rides_the_stack():
    m = 2
    lr = StreamingLogisticRegressionWithSGD
    mt = TenantStackModel(
        m,
        step_size=lr.default_step_size,
        residual_fn=lr.residual_fn,
        prediction_fn=lr.prediction_fn,
        round_predictions=lr.round_predictions,
    )
    singles = [lr() for _ in range(m)]
    rb = _ragged_batches()[0]
    parts = split_batch_tenants(rb, tenant_route_keys(rb, m), m)
    out = mt.step(rb)
    for i in range(m):
        oi = singles[i].step(parts[i])
        assert float(oi.mse) == float(out.mse[i])
    for i in range(m):
        assert singles[i].latest_weights.tobytes() == (
            mt.latest_weights[i].tobytes()
        )


def test_dry_tenant_stats_stay_finite_and_weights_frozen():
    """An all-padding tenant batch is a weight no-op with finite stats —
    the healthy-path guarantee the sentinel's aggregate check relies on."""
    mt = TenantStackModel(4)
    rb = _ragged_batches()[0]
    ids = np.zeros(rb.mask.shape[0], np.int32)  # tenants 1..3 dry
    wire = mt.prepare_wire_from_parts(split_batch_tenants(rb, ids, 4))
    out = mt.step(wire)
    host = np.asarray(out.mse)
    assert np.isfinite(host).all()
    assert float(np.asarray(out.count)[1]) == 0.0
    w = mt.latest_weights
    assert np.array_equal(w[1], np.zeros_like(w[1]))  # dry → untouched
    assert not np.array_equal(w[0], np.zeros_like(w[0]))


def test_aggregate_output_m4_exact_counts_and_mse():
    import jax

    mt = TenantStackModel(4)
    rb = _ragged_batches()[0]
    out = jax.device_get(mt.step(rb))
    agg = aggregate_tenant_output(out, rb, mt)
    counts = np.asarray(out.count, np.float64)
    assert float(agg.count) == counts.sum()
    want_mse = (counts * np.asarray(out.mse, np.float64)).sum() / counts.sum()
    # agg.mse is stored f32; compare at f32 resolution of the magnitude
    assert abs(float(agg.mse) - want_mse) <= max(1e-3, 1e-6 * want_mse)
    # predictions return in ORIGINAL row order: check against a per-tenant
    # manual scatter through the same deterministic route
    rows_per = tenant_rows(rb, mt.route_ids(rb), 4)
    for m, rows in enumerate(rows_per):
        assert np.array_equal(
            np.asarray(agg.predictions)[rows],
            np.asarray(out.predictions)[m][: rows.shape[0]],
        )


def test_nonfinite_tenant_poisons_the_aggregate():
    """One poisoned tenant must surface in the aggregate scalars — that is
    what routes the existing divergence sentinel onto the stacked plane."""
    import jax

    mt = TenantStackModel(2)
    rb = _ragged_batches()[0]
    out = jax.device_get(mt.step(rb))
    poisoned = out._replace(
        mse=np.array([out.mse[0], np.nan], np.float32)
    )
    agg = aggregate_tenant_output(poisoned, rb, mt)
    assert not np.isfinite(float(agg.mse))


def test_split_tenant_output_views():
    import jax

    mt = TenantStackModel(3)
    rb = _ragged_batches()[0]
    out = jax.device_get(mt.step(rb))
    parts = split_tenant_output(out, 3)
    assert len(parts) == 3
    for i, p in enumerate(parts):
        assert float(p.mse) == float(out.mse[i])


def test_checkpoint_roundtrip_and_flat_broadcast():
    mt = TenantStackModel(3)
    for rb in _ragged_batches():
        mt.step(rb)
    state = mt.latest_weights
    fresh = TenantStackModel(3)
    fresh.set_initial_weights(state)
    assert fresh.latest_weights.tobytes() == state.tobytes()
    # the sentinel's flat zeros reset broadcasts across tenants
    fresh.set_initial_weights(np.zeros(state.shape[1], np.float32))
    assert not fresh.latest_weights.any()


# ---------------------------------------------------------------------------
# mesh composition


def test_mesh_data_axis_composes(monkeypatch):
    import jax

    from twtml_tpu.parallel import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    m = 4
    ref = TenantStackModel(m)
    mesh = make_mesh(num_data=2, devices=jax.devices()[:2])
    mtm = TenantStackModel(m, mesh=mesh)
    mtg = TenantStackModel(m, mesh=mesh, wire_pack="group")
    for rb in _ragged_batches():
        ref.step(rb)
        mtm.step(rb)
        mtg.step(rb)
    # group wire bit-equals the stacked wire on the mesh (same program law)
    assert mtm.latest_weights.tobytes() == mtg.latest_weights.tobytes()
    # mesh vs single-device: same math, different psum association
    assert np.allclose(
        mtm.latest_weights, ref.latest_weights, rtol=1e-5, atol=1e-4
    )


def test_mesh_2d_tenant_axis_shards_tenants():
    import jax

    from twtml_tpu.parallel import make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")
    m = 4
    mesh2 = make_mesh(num_data=2, num_model=2, devices=jax.devices()[:4])
    mt2d = TenantStackModel(m, mesh=mesh2)
    mesh1 = make_mesh(num_data=2, devices=jax.devices()[:2])
    mt1d = TenantStackModel(m, mesh=mesh1)
    for rb in _ragged_batches():
        mt2d.step(rb)
        mt1d.step(rb)
    from jax.sharding import PartitionSpec as P

    assert mt2d._weights.sharding.spec == P("model", None)
    assert np.allclose(
        mt2d.latest_weights, mt1d.latest_weights, rtol=1e-5, atol=1e-4
    )


# ---------------------------------------------------------------------------
# app-level acceptance: one fetch per tick at M=8, M=1 app parity


CLOSED = "http://127.0.0.1:9"
BASE = [
    "--source", "replay", "--seconds", "0", "--backend", "cpu",
    "--batchBucket", "16", "--tokenBucket", "64", "--master", "local[1]",
    "--lightning", CLOSED, "--twtweb", CLOSED, "--webTimeout", "0.2",
]


def _corpus_file(tmp_path, total=8 * 16, seed=51):
    from tools.bench_suite import _status_json

    path = tmp_path / "tweets.jsonl"
    with open(path, "w") as fh:
        for s in SyntheticSource(total=total, seed=seed, base_ms=NOW_MS).produce():
            fh.write(json.dumps(_status_json(s)) + "\n")
    return path


def _run_counting_fetches(conf_args):
    import jax

    from twtml_tpu.apps import linear_regression as app

    jax.devices()
    calls = {"n": 0}
    real = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real(x)

    jax.device_get = counting
    try:
        totals = app.run(ConfArguments().parse(list(conf_args)))
    finally:
        jax.device_get = real
    return totals, calls["n"]


def test_app_m8_one_fetch_per_tick(tmp_path, monkeypatch):
    """ACCEPTANCE: a real M=8 app run fetches ONCE per dispatched batch —
    fetch count is independent of the tenant count (the whole point), and
    per-tenant rows conserve into the telemetry view."""
    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    path = _corpus_file(tmp_path)
    totals, fetches = _run_counting_fetches(
        BASE + ["--replayFile", str(path), "--tenants", "8"]
    )
    assert totals["batches"] == 8
    assert totals["tenants"] == 8
    assert fetches == 8  # ONE device_get per tick, M=8 notwithstanding
    view = _tenants_tel.last_tenants()
    assert view is not None and len(view["tenants"]) == 8
    # row conservation across the whole run
    assert sum(t["rows"] for t in view["tenants"]) == totals["count"] == 128
    assert view["gating"] == max(
        view["tenants"], key=lambda t: t["batch"]
    )["tenant"]
    reg = _metrics.get_registry().snapshot()
    assert reg["gauges"]["tenants.configured"] == 8


def test_app_m1_bit_parity_with_single_tenant_run(tmp_path, monkeypatch):
    """ACCEPTANCE: --tenants 1 produces byte-identical final weights AND
    published stats (the printed per-batch lines are the published stats)
    to a run without the flag."""
    import contextlib
    import io

    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.checkpoint import Checkpointer

    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    path = _corpus_file(tmp_path)

    def run(extra, ckdir):
        _metrics.reset_for_tests()
        _tenants_tel.reset_for_tests()
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            totals = app.run(ConfArguments().parse(
                BASE + ["--replayFile", str(path),
                        "--checkpointDir", str(ckdir),
                        "--checkpointEvery", "1"] + extra
            ))
        return totals, buf.getvalue()

    t1, out1 = run([], tmp_path / "ck_single")
    # TWTML_FORCE_TENANT_PLANE routes --tenants 1 through the stacked
    # program (the default path keeps the plain model — a 1-tenant
    # stream must not pay the routing split)
    monkeypatch.setenv("TWTML_FORCE_TENANT_PLANE", "1")
    t2, out2 = run(["--tenants", "1"], tmp_path / "ck_m1")
    monkeypatch.delenv("TWTML_FORCE_TENANT_PLANE")
    assert t1["batches"] == t2["batches"]
    assert out1 == out2  # published stats line-for-line identical
    w1, _ = Checkpointer(str(tmp_path / "ck_single")).restore()
    w2, _ = Checkpointer(str(tmp_path / "ck_m1")).restore()
    assert np.asarray(w1).tobytes() == np.asarray(w2)[0].tobytes()


def test_app_m4_sentinel_rolls_back_stacked_plane(tmp_path, monkeypatch):
    """A poisoned batch on the tenant plane: the aggregate stats go
    non-finite, the sentinel skips the batch and rolls the WHOLE stacked
    state back to the verified checkpoint — one guard for M models."""
    from twtml_tpu.streaming import faults

    monkeypatch.setenv("TWTML_NOW_MS", str(NOW_MS))
    path = _corpus_file(tmp_path)
    try:
        totals, fetches = _run_counting_fetches(
            BASE + ["--replayFile", str(path), "--tenants", "4",
                    "--checkpointDir", str(tmp_path / "ck"),
                    "--checkpointEvery", "1", "--chaos", "source.nan@5"]
        )
    finally:
        faults.uninstall_chaos()
    reg = _metrics.get_registry()
    assert reg.counter("model.rollbacks").snapshot() == 1
    # the sentinel skips the poisoned batch, and the r21 intake journal
    # replays its rows from disk (the journal seam sits upstream of the
    # poison injection point, so they re-featurize clean): all 8 batches
    # of the corpus end up trained, zero rows lost
    assert totals["batches"] == 8
    assert totals["count"] == 128
    assert reg.counter("model.rows_lost").snapshot() == 0
    assert reg.counter("journal.replayed_rows").snapshot() > 0
    # zero ADDED fetches: sentinel reads fetched stats — one fetch per
    # DISPATCHED batch (8 original + 1 replayed)
    assert fetches == 9


def test_conf_flags():
    conf = ConfArguments().parse(["--tenants", "4", "--tenantKey", "lang"])
    assert conf.tenants == 4 and conf.tenantKey == "lang"
    with pytest.raises(SystemExit):
        ConfArguments().parse(["--tenantKey", "bogus"])
    with pytest.raises(SystemExit):
        ConfArguments().parse(["--tenants", "0"])
