"""Durable intake journal (ISSUE 19): crash-equals-clean replay recovery.

Three layers, mirroring the journal's own contract:

- **Framing/disk units**: CRC-framed records round-trip bit-exactly for
  both seam item kinds (Status objects, ParsedBlocks in both units
  dtypes); a torn tail (kill -9 mid-append) is truncated LOUDLY; mid-
  history corruption RAISES instead of silently under-replaying; segments
  rotate, retire under checkpoint coverage, and the --journalMaxMb
  ceiling drops oldest-first, counted.
- **Cursor semantics**: the committed cursor advances on DELIVERY (the
  fetch pipeline dispatches ahead of delivery, so the tail is not safe to
  stamp), replay arms suppression + re-bases the cursor, and saves are
  deferred while a replay drains.
- **End-to-end**: a SIGKILL'd run restarted from its checkpoint + journal
  ends with weights BIT-EQUAL to an unfailed control over the same file
  (the acceptance differential), `--journal off` is bit-exact pre-journal
  behavior, and the healthy path adds zero host fetches.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from twtml_tpu.config import ConfArguments
from twtml_tpu.features.featurizer import Status
from twtml_tpu.streaming import journal as journal_mod
from twtml_tpu.streaming.journal import IntakeJournal
from twtml_tpu.telemetry import metrics as _metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLOSED = "http://127.0.0.1:9"


@pytest.fixture(autouse=True)
def _clean_metrics():
    _metrics.reset_for_tests()
    yield
    journal_mod.uninstall()
    _metrics.reset_for_tests()


def _statuses(n, tag="t", rt_every=3):
    out = []
    for i in range(n):
        rs = None
        if rt_every and i % rt_every == 0:
            rs = Status(
                text=f"original {tag} {i} é", retweet_count=i * 2,
                followers_count=100 + i, created_at_ms=1785310000000 + i,
                lang="fr", id=900000 + i,
            )
        out.append(Status(
            text=f"tweet {tag} {i} ünïcode", retweet_count=i,
            followers_count=10 + i, favourites_count=i % 7,
            friends_count=i % 5, created_at_ms=1785320000000 + i,
            retweeted_status=rs, lang="en", id=1000000 + i,
        ))
    return out


def _block(rows, dtype=np.uint8, seed=0):
    from twtml_tpu.features.blocks import ParsedBlock

    rng = np.random.RandomState(seed)
    numeric = rng.randint(0, 1000, size=(rows, 5)).astype(np.int64)
    lens = rng.randint(1, 9, size=rows)
    units = rng.randint(
        0, 255 if dtype == np.uint8 else 60000, size=int(lens.sum())
    ).astype(dtype)
    offsets = np.zeros(rows + 1, np.int64)
    offsets[1:] = np.cumsum(lens)
    ascii_col = (dtype == np.uint8) * np.ones(rows, np.uint8)
    return ParsedBlock(numeric, units, offsets, ascii_col)


# -- framing / disk units ----------------------------------------------------


def test_object_records_roundtrip_bit_parity(tmp_path):
    j = IntakeJournal(str(tmp_path / "j"))
    batches = [_statuses(16, "a"), _statuses(7, "b", rt_every=2)]
    for b in batches:
        j.append(b)
    j.close()
    j2 = IntakeJournal(str(tmp_path / "j"))
    assert j2.next_id == 2
    assert j2.rows_total == 23
    replayed = [items for _id, items in j2.records_from(0)]
    # dataclass equality over every field, recursively through
    # retweeted_status — what the featurizer reads is byte-identical
    assert replayed == batches


def test_block_records_roundtrip_both_dtypes(tmp_path):
    j = IntakeJournal(str(tmp_path / "j"))
    b8, b16 = _block(12, np.uint8, seed=1), _block(9, np.uint16, seed=2)
    j.append([b8])
    j.append([b16])
    assert j.rows_total == 21
    out = [items[0] for _id, items in j.records_from(0)]
    for orig, back in zip((b8, b16), out):
        assert back.units.dtype == orig.units.dtype
        np.testing.assert_array_equal(back.numeric, orig.numeric)
        np.testing.assert_array_equal(back.units, orig.units)
        np.testing.assert_array_equal(back.offsets, orig.offsets)
        np.testing.assert_array_equal(back.ascii, orig.ascii)


def test_torn_tail_truncated_loudly(tmp_path):
    d = str(tmp_path / "j")
    j = IntakeJournal(d)
    for i in range(3):
        j.append(_statuses(4, f"k{i}"))
    j.close()
    seg = [f for f in os.listdir(d) if f.endswith(".twj")]
    assert len(seg) == 1
    path = os.path.join(d, seg[0])
    size_before = os.path.getsize(path)
    # what a kill -9 mid-append leaves: a frame header + partial payload
    with open(path, "ab") as fh:
        fh.write(b"TWJL" + (9999).to_bytes(4, "little") + b"\x00" * 40)
    j2 = IntakeJournal(d)
    # every complete record survives, the torn bytes are gone, counted
    assert j2.next_id == 3
    assert j2.rows_total == 12
    assert os.path.getsize(path) == size_before
    assert _metrics.get_registry().counter(
        "journal.torn_tails").snapshot() == 1
    assert sum(len(it) for _i, it in j2.records_from(0)) == 12


def test_mid_history_corruption_raises(tmp_path):
    d = str(tmp_path / "j")
    # max_mb=4 -> segment_bytes floored to 1 MB; force rotation w/ big rows
    j = IntakeJournal(d, max_mb=4)
    big = [Status(text="x" * 300000, id=i) for i in range(8)]
    for s in big:
        j.append([s])  # ~300 KB/record -> rotates after ~4
    segs = sorted(f for f in os.listdir(d) if f.endswith(".twj"))
    assert len(segs) >= 2, "need a non-tail segment to corrupt"
    # flip a payload byte mid-way through the FIRST (non-tail) segment
    first = os.path.join(d, segs[0])
    with open(first, "r+b") as fh:
        fh.seek(os.path.getsize(first) // 2)
        b = fh.read(1)
        fh.seek(-1, os.SEEK_CUR)
        fh.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(RuntimeError, match="corrupt mid-history"):
        list(j.records_from(0))
    j.close()


def test_rotation_retirement_and_disk_ceiling(tmp_path):
    d = str(tmp_path / "j")
    j = IntakeJournal(d, max_mb=4)  # segment_bytes floored to 1 MB
    big = [Status(text="y" * 200000, id=i) for i in range(30)]
    for s in big:
        j.append([s])
    reg = _metrics.get_registry()
    # ~6 MB appended against a 4 MB hard ceiling: oldest segments
    # dropped loudly, disk stays bounded
    assert reg.counter("journal.segments_dropped").snapshot() >= 1
    assert j.disk_bytes() <= 4 * 1024 * 1024 + 1024 * 1024  # +active slack
    segs = sorted(f for f in os.listdir(d) if f.endswith(".twj"))
    assert len(segs) >= 2
    # retirement: a verified-checkpoint cursor past a whole segment
    # unlinks it (never the active tail segment)
    first_alive = int(re.match(r"seg-(\d+)\.twj", segs[0]).group(1))
    cursor_past_first = int(re.match(r"seg-(\d+)\.twj", segs[1]).group(1))
    retired = j.retire_covered(cursor_past_first)
    assert retired == 1
    assert first_alive not in [
        int(re.match(r"seg-(\d+)\.twj", f).group(1))
        for f in os.listdir(d) if f.endswith(".twj")
    ]
    # the active segment never retires, even with a cursor at the tail
    j.retire_covered(j.next_id)
    assert any(f.endswith(".twj") for f in os.listdir(d))
    j.close()


def test_replay_suppression_and_mixed_batch(tmp_path):
    j = IntakeJournal(str(tmp_path / "j"))
    a, b = _statuses(16, "a"), _statuses(16, "b")
    j.append(a)
    j.append(b)
    items, rows = j.replay_from(1)
    assert rows == 16 and [s.id for s in items] == [s.id for s in b]
    # the replayed rows re-cross the seam: the first 16 rows are NOT
    # re-appended, and a mixed batch (replayed head + fresh tail in one
    # drain) appends only the fresh tail
    fresh = _statuses(4, "c")
    j.append(b[:10])          # fully suppressed
    assert j.rows_total == 32
    j.append(b[10:] + fresh)  # 6 suppressed + 4 fresh appended
    assert j.rows_total == 36
    assert j.next_id == 3
    tail = list(j.records_from(2))
    assert [s.id for s in tail[0][1]] == [s.id for s in fresh]
    j.close()


# -- dispatch-token committed cursor -----------------------------------------


def test_committed_cursor_advances_on_delivery_not_dispatch(tmp_path):
    j = IntakeJournal(str(tmp_path / "j"))
    # two batches cross the seam (append + token push), none delivered:
    # the checkpoint stamp must NOT cover them
    j.append(_statuses(16, "a")); j.push_dispatch()
    j.append(_statuses(16, "b")); j.push_dispatch()
    assert j.snapshot_for_checkpoint() == {"cursor": 0, "rows": 0}
    # first delivery commits its own token only
    j.pop_dispatch(); j.note_delivered()
    assert j.snapshot_for_checkpoint() == {"cursor": 1, "rows": 16}
    # a delivery an admission filter skipped pops WITHOUT committing
    j.pop_dispatch()
    assert j.snapshot_for_checkpoint() == {"cursor": 1, "rows": 16}
    j.close()


def test_replay_rebases_cursor_and_defers_saves(tmp_path):
    j = IntakeJournal(str(tmp_path / "j"))
    for tag in "abc":
        j.append(_statuses(8, tag)); j.push_dispatch()
        j.pop_dispatch(); j.note_delivered()
    assert j.snapshot_for_checkpoint() == {"cursor": 3, "rows": 24}
    items, rows = j.replay_from(1)
    assert rows == 16
    # the restored weights cover [0, 1): saves hold until the replay drains
    assert j.snapshot_for_checkpoint() == {"cursor": 1, "rows": 8}
    assert not j.save_allowed
    # mid-replay batch: suppressed append, token is None -> no commit
    j.append(items[:8]); j.push_dispatch()
    j.pop_dispatch(); j.note_delivered()
    assert not j.save_allowed
    assert j.snapshot_for_checkpoint() == {"cursor": 1, "rows": 8}
    # the batch that drains suppression to zero pushes the REAL tail;
    # its delivery re-opens saves with every journaled row covered
    j.append(items[8:]); j.push_dispatch()
    j.pop_dispatch(); j.note_delivered()
    assert j.save_allowed
    assert j.snapshot_for_checkpoint() == {"cursor": 3, "rows": 24}
    j.close()


def test_shed_and_reform_token_hygiene(tmp_path):
    j = IntakeJournal(str(tmp_path / "j"))
    j.append(_statuses(8, "a")); j.push_dispatch()
    # single-host shed: the batch never dispatches — un-push, then the
    # next real delivery pairs with its own token
    j.drop_newest()
    j.append(_statuses(8, "b")); j.push_dispatch()
    j.pop_dispatch(); j.note_delivered()
    assert j.snapshot_for_checkpoint()["cursor"] == 2
    # elastic reform: in-flight deliveries discarded wholesale
    j.append(_statuses(8, "c")); j.push_dispatch()
    j.clear_inflight()
    j.pop_dispatch()  # a stray late pop finds an empty FIFO: no commit
    j.note_delivered()
    assert j.snapshot_for_checkpoint()["cursor"] == 2
    j.close()


# -- end-to-end --------------------------------------------------------------


def _write_corpus(path, total, seed):
    from tools.bench_suite import _status_json
    from twtml_tpu.streaming.sources import SyntheticSource

    with open(path, "w") as fh:
        for s in SyntheticSource(
            total=total, seed=seed, base_ms=1785320000000
        ).produce():
            fh.write(json.dumps(_status_json(s)) + "\n")


BASE = [
    "--source", "replay", "--seconds", "0", "--backend", "cpu",
    "--batchBucket", "16", "--tokenBucket", "64", "--master", "local[1]",
    "--lightning", CLOSED, "--twtweb", CLOSED, "--webTimeout", "0.2",
]


def test_checkpoint_stamp_roundtrip_and_journal_off_bit_exact(tmp_path,
                                                              monkeypatch):
    """Healthy path: the save stamps the journal cursor into verified
    checkpoint meta (cursor == batches delivered, rows == rows trained),
    and --journal off produces BIT-identical weights and the same fetch
    count — the journal's healthy-path cost is host-disk only."""
    import jax

    from twtml_tpu.apps import linear_regression as app
    from twtml_tpu.checkpoint import Checkpointer

    jax.devices()
    monkeypatch.setenv("TWTML_NOW_MS", "1785320000000")
    path = tmp_path / "tweets.jsonl"
    _write_corpus(path, 6 * 16, seed=71)

    def run(ckdir, *extra):
        calls = {"n": 0}
        real = jax.device_get

        def counting(x):
            calls["n"] += 1
            return real(x)

        jax.device_get = counting
        try:
            totals = app.run(ConfArguments().parse(
                BASE + ["--replayFile", str(path), "--checkpointDir",
                        ckdir, "--checkpointEvery", "2", *extra]
            ))
        finally:
            jax.device_get = real
        return totals, calls["n"]

    d_on, d_off = str(tmp_path / "on"), str(tmp_path / "off")
    totals_on, fetches_on = run(d_on)
    stamp = Checkpointer(d_on).latest_meta()["journal"]
    assert stamp == {"cursor": 6, "rows": 6 * 16}
    assert journal_mod.get() is None  # run() uninstalls on the way out

    _metrics.reset_for_tests()
    totals_off, fetches_off = run(d_off, "--journal", "off")
    assert "journal" not in Checkpointer(d_off).latest_meta()
    assert (totals_on["count"], totals_on["batches"]) == (
        totals_off["count"], totals_off["batches"]) == (6 * 16, 6)
    # zero added host fetches on the healthy path (counted, the
    # measurement-integrity idiom)
    assert fetches_on == fetches_off
    w_on, _ = Checkpointer(d_on).restore()
    w_off, _ = Checkpointer(d_off).restore()
    np.testing.assert_array_equal(w_on, w_off)


_KILL_DRIVER = """
import os, signal, sys
sys.path.insert(0, {repo!r})
from twtml_tpu.checkpoint.checkpointer import Checkpointer
orig = Checkpointer.save
state = {{"n": 0}}
def save(self, step, weights, metadata=None):
    out = orig(self, step, weights, metadata)
    state["n"] += 1
    if state["n"] == 3:
        os.kill(os.getpid(), signal.SIGKILL)  # hard death mid-stream
    return out
Checkpointer.save = save
from twtml_tpu.apps import linear_regression as app
app.main(sys.argv[1:])
"""


def test_sigkill_restart_weights_equal_unfailed_control(tmp_path):
    """THE acceptance differential: a run SIGKILL'd mid-stream (right
    after its 3rd cadence save, queue and fetch pipeline full of
    in-flight rows) and restarted ends with weights np.array_equal to a
    control run that never failed — zero rows lost, zero double-trained,
    proven on the final checkpoint of each."""
    from twtml_tpu.checkpoint import Checkpointer

    corpus = tmp_path / "tweets.jsonl"
    _write_corpus(corpus, 12 * 16, seed=72)
    env = dict(
        os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
        TWTML_NOW_MS="1785320000000",
    )
    driver = tmp_path / "kill_driver.py"
    driver.write_text(_KILL_DRIVER.format(repo=REPO))
    ck_kill = str(tmp_path / "ck_kill")
    args = BASE + ["--replayFile", str(corpus), "--checkpointDir", ck_kill,
                   "--checkpointEvery", "1"]
    proc = subprocess.run(
        [sys.executable, str(driver), *args],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-3000:]
    saved = Checkpointer(ck_kill).latest_meta()
    assert saved is not None and saved["batches"] < 12  # died mid-stream

    # second life: plain restart, same flags — checkpoint restore +
    # journal boot replay + source fast-forward must reconstruct exactly
    proc2 = subprocess.run(
        [sys.executable, "-m", "twtml_tpu.apps.linear_regression", *args],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO,
    )
    assert proc2.returncode == 0, proc2.stderr[-3000:]
    assert "journal: boot resume" in proc2.stderr

    ck_ctrl = str(tmp_path / "ck_ctrl")
    proc3 = subprocess.run(
        [sys.executable, "-m", "twtml_tpu.apps.linear_regression",
         *(BASE + ["--replayFile", str(corpus), "--checkpointDir", ck_ctrl,
                   "--checkpointEvery", "1"])],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO,
    )
    assert proc3.returncode == 0, proc3.stderr[-3000:]

    w_kill, meta_kill = Checkpointer(ck_kill).restore()
    w_ctrl, meta_ctrl = Checkpointer(ck_ctrl).restore()
    assert meta_kill["count"] == meta_ctrl["count"] == 12 * 16
    assert meta_kill["batches"] == meta_ctrl["batches"] == 12
    np.testing.assert_array_equal(np.asarray(w_kill), np.asarray(w_ctrl))
