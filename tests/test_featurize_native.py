"""One-pass native featurize (ISSUE 15, r18).

The fused C emitter (native/featurize.cpp via
features/featurize_native.py) must produce batches BIT-IDENTICAL — every
array, every dtype, the row_len aux — to the Python/numpy ground truth
in features/featurizer.py on both ingest paths, across the Unicode edge
cases the wire formats care about (astral pairs, lone surrogates,
length-changing lowercasing, accent mode), every labeler variant, and
the empty batch; trained-weight trajectories must be bitwise-equal with
the featurizer on vs off (single device, 4-way mesh, tenant stack). The
arena lease riding the batch retires exactly once — on fetch delivery
through the dispatch pipelines (chained with the wire lease), or via
the GC ``discard`` backstop for batches that never dispatch. The
stale-library degrade seam mirrors r6/r15/r17's: a real .so without
``featurize_wire`` loads, flags once, and featurize keeps flowing
through Python.
"""

from __future__ import annotations

import gc
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from twtml_tpu.features import arena as arena_mod  # noqa: E402
from twtml_tpu.features import featurize_native as ffz  # noqa: E402
from twtml_tpu.features import native  # noqa: E402
from twtml_tpu.features.batch import pack_batch  # noqa: E402
from twtml_tpu.features.blocks import ParsedBlock  # noqa: E402
from twtml_tpu.features.featurizer import Featurizer, Status  # noqa: E402
from twtml_tpu.streaming.sources import SyntheticSource  # noqa: E402

needs_native = pytest.mark.skipif(
    not native.featurize_available(),
    reason="native featurize emitter unavailable (no g++?)",
)

NOW = 1785320000000


# ---------------------------------------------------------------------------
# builders


def synthetic(n=256):
    return list(SyntheticSource(total=n, seed=3, base_ms=NOW).produce())


def rt(text, count=500, **extra) -> Status:
    fields = dict(
        followers_count=1234, favourites_count=77, friends_count=450,
        created_at_ms=NOW - 86_400_000,
    )
    fields.update(extra)
    return Status(
        text="RT", retweet_count=1,
        retweeted_status=Status(
            text=text, retweet_count=count, **fields
        ),
    )


def unicode_corpus() -> list[Status]:
    """Every Unicode shape the wire formats special-case, plus filter
    variety (non-retweets, out-of-interval counts)."""
    return [
        rt("plain ascii tweet with CAPS and a link https://t.co/x"),
        rt("astral emoji \U0001f98a pair rides two UTF-16 units"),
        rt("lone surrogate \ud83e stays a unit like the JVM"),
        rt("İstanbul lowercases to MORE units (i + combining dot)"),
        rt("café naïve résumé — accents"),
        rt(""),  # empty original text
        rt("boundary low", count=100),
        rt("boundary high", count=1000),
        rt("dropped: below interval", count=99),
        rt("dropped: above interval", count=1001),
        Status(text="not a retweet at all"),
        rt("big numbers", followers_count=2**40,
           favourites_count=10**15, created_at_ms=0),
    ]


def assert_same_batch(ref, got, tag=""):
    for f in ("units", "offsets", "numeric", "label", "mask"):
        a, b = getattr(ref, f), getattr(got, f)
        assert a.dtype == b.dtype, (tag, f, a.dtype, b.dtype)
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"{tag}.{f}"
        )
    assert ref.row_len == got.row_len, (tag, ref.row_len, got.row_len)


def both_modes(fn):
    with ffz.forced("off"):
        ref = fn()
    with ffz.forced("on"):
        got = fn()
    return ref, got


def block_from(statuses) -> ParsedBlock:
    """Parse the statuses' JSONL through the native wire parser."""
    import json

    from tools.bench_suite import _status_json

    data = (
        "\n".join(json.dumps(_status_json(s)) for s in statuses) + "\n"
    ).encode("utf-8")
    parsed = native.parse_tweet_block_wire(data, 0, 10**9)
    assert parsed is not None
    return ParsedBlock(*parsed[:4])


# ---------------------------------------------------------------------------
# object-path bit parity


@needs_native
@pytest.mark.parametrize("row_bucket", [0, 64])
@pytest.mark.parametrize("pre_filtered", [False, True])
def test_object_parity_synthetic(row_bucket, pre_filtered):
    feat = Featurizer(now_ms=NOW)
    sts = synthetic(200)
    ref, got = both_modes(
        lambda: feat.featurize_batch_ragged(
            sts, row_bucket=row_bucket, pre_filtered=pre_filtered
        )
    )
    assert_same_batch(ref, got, "synthetic")
    assert got.num_valid == 200


@needs_native
def test_object_parity_unicode_edges():
    feat = Featurizer(now_ms=NOW)
    ref, got = both_modes(
        lambda: feat.featurize_batch_ragged(unicode_corpus(), row_bucket=16)
    )
    assert_same_batch(ref, got, "unicode")
    # the corpus mixes ASCII and non-ASCII rows: the wide wire must ship
    assert ref.units.dtype == np.uint16


@needs_native
def test_object_parity_all_ascii_narrow_wire():
    feat = Featurizer(now_ms=NOW)
    sts = [rt("pure ascii %d" % i) for i in range(10)]
    ref, got = both_modes(
        lambda: feat.featurize_batch_ragged(sts, row_bucket=16)
    )
    assert_same_batch(ref, got, "ascii")
    assert ref.units.dtype == np.uint8  # the narrow wire, both modes


@needs_native
def test_object_parity_empty_batch():
    feat = Featurizer(now_ms=NOW)
    ref, got = both_modes(
        lambda: feat.featurize_batch_ragged([], row_bucket=32)
    )
    assert_same_batch(ref, got, "empty")
    assert got.num_valid == 0


@needs_native
def test_object_parity_accent_mode():
    feat = Featurizer(now_ms=NOW, normalize_accents=True)
    ref, got = both_modes(
        lambda: feat.featurize_batch_ragged(unicode_corpus(), row_bucket=16)
    )
    assert_same_batch(ref, got, "accents")


@needs_native
def test_object_parity_label_fn_variants():
    corpus = synthetic(64) + unicode_corpus()
    # per-status label_fn
    f1 = Featurizer(
        now_ms=NOW,
        label_fn=lambda s: s.retweeted_status.followers_count * 0.25,
    )
    ref, got = both_modes(
        lambda: f1.featurize_batch_ragged(corpus, row_bucket=128)
    )
    assert_same_batch(ref, got, "label_fn")
    # batched labeler (encoded= contract included)
    from twtml_tpu.features.sentiment import sentiment_label, sentiment_labels

    f2 = Featurizer(
        now_ms=NOW, label_fn=sentiment_label, batch_label_fn=sentiment_labels
    )
    ref, got = both_modes(
        lambda: f2.featurize_batch_ragged(corpus, row_bucket=128)
    )
    assert_same_batch(ref, got, "batch_label_fn")
    assert np.asarray(ref.label)[: ref.num_valid].any()  # labels are live


@needs_native
def test_object_parity_subclassed_filtrate():
    class OddFilter(Featurizer):
        def filtrate(self, s):
            return s.is_retweet and (
                s.retweeted_status.retweet_count % 2 == 0
            )

    feat = OddFilter(now_ms=NOW)
    sts = [rt("tweet %d" % i, count=100 + i) for i in range(30)]
    ref, got = both_modes(
        lambda: feat.featurize_batch_ragged(sts, row_bucket=32)
    )
    assert_same_batch(ref, got, "subclass")
    assert got.num_valid == 15  # the subclass filter actually applied


# ---------------------------------------------------------------------------
# block-path bit parity


@needs_native
def test_block_parity_ascii_common_case():
    feat = Featurizer(now_ms=NOW)
    block = block_from([rt("block ascii row %d" % i) for i in range(40)])
    assert block.units.dtype == np.uint8
    ref, got = both_modes(
        lambda: feat.featurize_parsed_block(block, row_bucket=64, ragged=True)
    )
    assert_same_batch(ref, got, "block-ascii")
    assert got.units.dtype == np.uint8


@needs_native
def test_block_parity_uint16_legacy_parser_units():
    """A legacy (ParsedBlock-parser) block carries uint16 units even when
    every row is ASCII — the fused path must downcast identically."""
    feat = Featurizer(now_ms=NOW)
    blk = block_from([rt("legacy width row %d" % i) for i in range(12)])
    wide = ParsedBlock(
        blk.numeric, blk.units.astype(np.uint16), blk.offsets, blk.ascii
    )
    ref, got = both_modes(
        lambda: feat.featurize_parsed_block(wide, row_bucket=16, ragged=True)
    )
    assert_same_batch(ref, got, "block-u16")
    assert got.units.dtype == np.uint8  # ascii-flagged → narrow wire


@needs_native
def test_block_nonascii_and_accent_rows_fall_back_identically():
    feat = Featurizer(now_ms=NOW)
    block = block_from(
        [rt("ascii row"), rt("unicode İ row \U0001f98a")] * 4
    )
    ref, got = both_modes(
        lambda: feat.featurize_parsed_block(block, row_bucket=16, ragged=True)
    )
    assert_same_batch(ref, got, "block-nonascii")
    feat2 = Featurizer(now_ms=NOW, normalize_accents=True)
    ref, got = both_modes(
        lambda: feat2.featurize_parsed_block(
            block, row_bucket=16, ragged=True
        )
    )
    assert_same_batch(ref, got, "block-accents")


@needs_native
def test_block_parity_unit_label_fn():
    from twtml_tpu.features.sentiment import sentiment_labels_from_units

    feat = Featurizer(now_ms=NOW, unit_label_fn=sentiment_labels_from_units)
    block = block_from(
        [rt("good happy great row"), rt("bad awful terrible row")] * 5
    )
    ref, got = both_modes(
        lambda: feat.featurize_parsed_block(block, row_bucket=16, ragged=True)
    )
    assert_same_batch(ref, got, "block-unit-labels")
    lab = np.asarray(got.label)[: got.num_valid]
    assert lab.any()  # the lexicon labels applied (not the count column)


@needs_native
def test_block_parity_empty_block():
    from twtml_tpu.features.blocks import empty_block

    feat = Featurizer(now_ms=NOW)
    ref, got = both_modes(
        lambda: feat.featurize_parsed_block(
            empty_block(), row_bucket=8, ragged=True
        )
    )
    assert_same_batch(ref, got, "block-empty")


@needs_native
def test_block_packed_wire_byte_parity():
    """featurize → pack: the packed wire (the bytes the tunnel sees) is
    byte-identical with the fused featurize on vs off."""
    feat = Featurizer(now_ms=NOW)
    block = block_from([rt("packed row %d" % i) for i in range(32)])
    ref, got = both_modes(
        lambda: feat.featurize_parsed_block(
            block, row_bucket=32, ragged=True, pack=True
        )
    )
    assert ref.layout == got.layout
    np.testing.assert_array_equal(
        np.asarray(ref.buffer), np.asarray(got.buffer)
    )


@needs_native
@pytest.mark.parametrize("codec", [None, "dict"])
@pytest.mark.parametrize("form", ["flat", "sharded", "group"])
def test_packed_wire_parity_every_form(form, codec):
    """featurize on vs off → every packed wire form × codec: the bytes
    the tunnel sees are identical (flat pack, shard-aligned pack,
    coalesced group pack)."""
    from twtml_tpu.features.batch import (
        align_ragged_shards, pack_ragged_group, pack_ragged_sharded,
    )

    feat = Featurizer(now_ms=NOW)
    sts = synthetic(128)

    def build(mode):
        with ffz.forced(mode):
            batches = [
                feat.featurize_batch_ragged(
                    sts[i : i + 32], row_bucket=32, unit_bucket=256,
                    pre_filtered=True,
                )
                for i in range(0, 128, 32)
            ]
        if form == "flat":
            return pack_batch(batches[0], codec=codec)
        if form == "sharded":
            return pack_ragged_sharded(
                align_ragged_shards(batches[0], 2), codec=codec
            )
        return pack_ragged_group(batches, codec=codec)

    ref, got = build("off"), build("on")
    assert ref.layout == got.layout
    np.testing.assert_array_equal(
        np.asarray(ref.buffer), np.asarray(got.buffer)
    )


# ---------------------------------------------------------------------------
# trajectory parity: trained weights bitwise-equal on vs off


def _featurized(feat, n=6, rows=32, mode="off"):
    sts = synthetic(n * rows)
    with ffz.forced(mode):
        return [
            feat.featurize_batch_ragged(
                sts[i * rows : (i + 1) * rows], row_bucket=rows,
                pre_filtered=True,
            )
            for i in range(n)
        ]


@needs_native
def test_trajectory_bitwise_single_device():
    from twtml_tpu.models import StreamingLinearRegressionWithSGD

    feat = Featurizer(now_ms=NOW)
    finals = {}
    for mode in ("off", "on"):
        m = StreamingLinearRegressionWithSGD(num_iterations=5)
        for b in _featurized(feat, mode=mode):
            m.step(pack_batch(b))
        finals[mode] = np.asarray(m.latest_weights)
    np.testing.assert_array_equal(finals["off"], finals["on"])


@needs_native
def test_trajectory_bitwise_mesh():
    import jax

    from twtml_tpu.parallel import ParallelSGDModel, make_mesh

    feat = Featurizer(now_ms=NOW)
    finals = {}
    for mode in ("off", "on"):
        mesh = make_mesh(num_data=4, devices=jax.devices()[:4])
        m = ParallelSGDModel(mesh, num_iterations=5, step_size=0.05)
        for b in _featurized(feat, n=4, mode=mode):
            m.step(m.pack_for_wire(b))
        finals[mode] = np.asarray(m.latest_weights)
    np.testing.assert_array_equal(finals["off"], finals["on"])


@needs_native
def test_trajectory_bitwise_tenant_stack():
    from twtml_tpu.parallel import TenantStackModel

    feat = Featurizer(now_ms=NOW)
    finals = {}
    for mode in ("off", "on"):
        mt = TenantStackModel(
            3, num_iterations=5, step_size=0.1, wire_pack="group"
        )
        for b in _featurized(feat, n=4, mode=mode):
            mt.step(b)
        finals[mode] = np.asarray(mt.latest_weights)
    np.testing.assert_array_equal(finals["off"], finals["on"])


# ---------------------------------------------------------------------------
# arena lease accounting


@pytest.fixture()
def private_arena(monkeypatch):
    """A fresh arena swapped in for the process-global one: the suite
    runs with --featurizeNative auto (= on), so batches from OTHER
    tests hold leases on the global arena and their GC finalizers fire
    at unpredictable points — absolute accounting assertions need an
    arena only this test's leases touch (old leases keep a reference to
    the arena THEY came from, so strays never land here)."""
    fresh = arena_mod.WireArena()
    monkeypatch.setattr(arena_mod, "_arena", fresh)
    return fresh


@needs_native
def test_featurize_leases_retire_on_pipeline_delivery(private_arena):
    """The featurize lease chains with the wire lease at the dispatch
    site and retires on fetch delivery — arena accounting returns to
    zero outstanding after the pipeline drains."""
    from twtml_tpu.apps.common import FetchPipeline

    class _EchoModel:
        accepts_packed = True

        def step(self, wire):
            return {"mse": np.float32(1.0)}

    feat = Featurizer(now_ms=NOW)
    delivered = []
    pipe = FetchPipeline(
        _EchoModel(), lambda out, b, t, at_boundary: delivered.append(b),
        depth=4,
    )
    with ffz.forced("on"):
        sts = synthetic(5 * 16)
        for i in range(5):
            b = feat.featurize_batch_ragged(
                sts[i * 16 : (i + 1) * 16], row_bucket=16,
                pre_filtered=True,
            )
            assert b._lease is not None
            pipe.on_batch(b, float(i))
        pipe.flush()
    assert len(delivered) == 5
    assert private_arena.stats()["in_use"] == 0


@needs_native
def test_featurize_lease_gc_backstop_discards(private_arena):
    """A featurized batch that never reaches a dispatch site releases
    its lease through the GC finalizer: accounting exact, buffer NOT
    pooled (discard — views extracted from the batch can never alias a
    recycled buffer)."""
    feat = Featurizer(now_ms=NOW)
    with ffz.forced("on"):
        b = feat.featurize_batch_ragged(synthetic(16), row_bucket=16)
    assert b._lease is not None
    assert private_arena.stats()["in_use"] == 1
    del b
    gc.collect()
    stats = private_arena.stats()
    assert stats["in_use"] == 0
    assert stats["free_buffers"] == 0  # discarded, never pooled


@needs_native
def test_featurize_lease_recycles_across_batches(private_arena):
    """Delivery-retired featurize buffers are POOLED: the second batch
    of the same signature reuses the first one's buffer."""
    feat = Featurizer(now_ms=NOW)
    sts = synthetic(32)
    with ffz.forced("on"):
        b1 = feat.featurize_batch_ragged(sts[:16], row_bucket=16,
                                         pre_filtered=True)
        buf1 = b1._lease.buf
        b1._lease.retire()
        b2 = feat.featurize_batch_ragged(sts[16:], row_bucket=16,
                                         pre_filtered=True)
        assert b2._lease.buf is buf1
        b2._lease.retire()


def test_chain_leases_combinator():
    from twtml_tpu.features.arena import LeaseChain, chain_leases

    a = arena_mod.WireArena()
    l1, l2 = a.lease(64), a.lease(128)
    assert chain_leases(None, None) is None
    assert chain_leases(l1, None) is l1
    assert chain_leases(l1, l1) is l1  # identity-deduplicated
    chain = chain_leases(l1, l2)
    assert isinstance(chain, LeaseChain)
    assert chain.buf is l1.buf  # primary buffer exposed
    chain.retire()
    assert a.stats()["in_use"] == 0
    assert a.stats()["free_buffers"] == 2
    # discard path: idempotent with the retire above
    chain.discard()
    assert a.stats()["free_buffers"] == 2


# ---------------------------------------------------------------------------
# zero added fetches: the sub-stage gauges are host clocks only


@needs_native
def test_substage_gauges_add_zero_fetches(monkeypatch):
    import jax

    from twtml_tpu.features.featurizer import Featurizer as F
    from twtml_tpu.streaming.context import FeatureStream
    from twtml_tpu.telemetry import metrics as _metrics

    calls = {"n": 0}
    real = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "device_get", counting)
    feat = F(now_ms=NOW)
    stream = FeatureStream(feat, row_bucket=16, device_hash=True,
                           ragged=True)
    with ffz.forced("on"):
        stream._featurize(synthetic(16))
    assert calls["n"] == 0  # featurize + gauges never fetch
    reg = _metrics.get_registry()
    snap = reg.snapshot()["gauges"]
    for name in ("featurize.encode_ms", "featurize.wire_build_ms"):
        assert name in snap, snap.keys()


# ---------------------------------------------------------------------------
# mode plumbing + degrade seam


def test_configure_validates():
    with pytest.raises(ValueError):
        ffz.configure("maybe")
    prev = ffz.mode()
    ffz.configure("off")
    assert not ffz.available()
    ffz.configure(prev)


def test_conf_flag_roundtrip():
    from twtml_tpu.config import ConfArguments

    conf = ConfArguments().parse(["--featurizeNative", "off"])
    assert conf.featurizeNative == "off"
    with pytest.raises(SystemExit):
        ConfArguments().parse(["--featurizeNative", "sometimes"])


def test_bind_featurize_flags_missing_symbol_and_counts(monkeypatch):
    from twtml_tpu.telemetry import metrics as _metrics

    class _NoFeaturize:
        def __getattr__(self, name):
            raise AttributeError(name)

    _metrics.reset_for_tests()
    monkeypatch.setattr(native, "_featurize_missing", False)
    with pytest.raises(AttributeError):
        native._bind_featurize(_NoFeaturize(), strict=True)
    native._bind_featurize(_NoFeaturize(), strict=False)
    assert native._featurize_missing
    assert _metrics.get_registry().counter(
        "native.featurize_degraded"
    ).snapshot() == 1
    monkeypatch.setattr(native, "_featurize_missing", False)


def test_featurize_missing_degrades_to_python(monkeypatch):
    monkeypatch.setattr(native, "_featurize_missing", True)
    assert not native.featurize_available()
    assert not ffz.available()
    feat = Featurizer(now_ms=NOW)
    with ffz.forced("on"):  # even explicit on degrades, never dies
        got = feat.featurize_batch_ragged(synthetic(16), row_bucket=16)
    monkeypatch.setattr(native, "_featurize_missing", False)
    with ffz.forced("off"):
        ref = feat.featurize_batch_ragged(synthetic(16), row_bucket=16)
    assert_same_batch(ref, got, "degraded")
    assert getattr(got, "_lease", None) is None  # python path: no lease


def test_stale_library_without_featurize_symbol_loads_degraded(tmp_path):
    """End-to-end seam: a REAL .so carrying every pre-r18 symbol but not
    ``featurize_wire`` loads with strict=False, flags the degrade, and
    keeps the old symbols callable — no ctypes AttributeError
    mid-stream."""
    src = tmp_path / "stale.cpp"
    src.write_text(
        """
#include <cstdint>
extern "C" {
int32_t fasthash_batch(uint16_t*, int64_t*, int32_t, int32_t, int32_t,
                       int32_t*, float*, int32_t*, int32_t) { return 0; }
int32_t pad_units_batch(uint16_t*, int64_t*, int32_t, int32_t, int32_t,
                        int32_t, uint16_t*, int32_t*) { return 0; }
int32_t pad_units_batch_u8(uint16_t*, int64_t*, int32_t, int32_t, int32_t,
                           int32_t, uint8_t*, int32_t*) { return 0; }
void lexicon_score_batch(uint16_t*, int64_t*, int32_t, uint16_t*, int64_t*,
                         int32_t*, int32_t, uint16_t*, int64_t*, int32_t*,
                         int32_t, int32_t*, uint8_t*) {}
int64_t parse_tweet_block(const char*, int64_t, int64_t, int64_t, int64_t,
                          int64_t, int64_t*, uint16_t*, int64_t*, uint8_t*,
                          int64_t* c, int64_t* b) { *c = 0; *b = 0; return 0; }
int64_t parse_tweet_block_wire(const char*, int64_t, int64_t, int64_t,
                               int64_t, int64_t, int64_t*, uint8_t*,
                               uint16_t*, int64_t*, uint8_t*, int64_t* c,
                               int64_t* b, int64_t* n, int64_t* w) {
  *c = 0; *b = 0; *n = 1; *w = 0; return 0; }
int64_t digram_encode(const uint8_t*, int64_t, const uint8_t*, uint8_t*,
                      int64_t) { return 0; }
int64_t wire_assemble(const void* const*, const int32_t* const*,
                      const float* const*, const float* const*,
                      const float* const*, int64_t, int64_t, int64_t,
                      int64_t, int64_t, int64_t, const uint8_t*, int64_t,
                      uint8_t*, int64_t*, uint8_t*, int64_t,
                      int64_t* e) { *e = 0; return 0; }
}
""",
        encoding="utf-8",
    )
    so = tmp_path / "stale.so"
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", "-o", str(so), str(src)],
        check=True, capture_output=True,
    )
    saved = native._featurize_missing
    try:
        with pytest.raises(AttributeError):
            native._load(str(so), strict=True)
        lib = native._load(str(so), strict=False)
        assert native._featurize_missing
        assert lib.wire_assemble is not None  # old symbols still bound
    finally:
        native._featurize_missing = saved
        # every degrade flag, not just ours (see test_blockwire's seam
        # test: a partial restore leaves sibling fast paths off)
        native.rebind_flags()


@needs_native
def test_fused_counter_increments():
    from twtml_tpu.telemetry import metrics as _metrics

    reg = _metrics.get_registry()
    before = reg.counter("featurize.fused_native").snapshot()
    feat = Featurizer(now_ms=NOW)
    with ffz.forced("on"):
        b = feat.featurize_batch_ragged(synthetic(16), row_bucket=16)
    assert reg.counter("featurize.fused_native").snapshot() == before + 1
    b._lease.retire()
