"""Lean wire v2 (ISSUE 3): the coalesced one-buffer superbatch wire
(``pack_ragged_group``) and the narrow uint16-delta offset wire must be
BYTE-IDENTICAL in features, per-batch stats, and final weights to the
shipped packed-ragged path — single-device AND sharded layouts, K ∈
{1, 4, 8} — with the int32 offset fallback metadata-gated exactly like the
uint8/uint16 units switch (rows longer than the uint16 delta range trip
it). The wire may change transfer count and sideband bytes, never math."""

import numpy as np
import pytest

import jax

from twtml_tpu.features.batch import (
    OFFSET_DELTA_MAX,
    RaggedUnitBatch,
    offsets_narrow,
    pack_batch,
    pack_ragged_group,
    pack_ragged_sharded,
    ragged_wire_arrays,
    stack_batches,
    unpack_batch,
    wire_composition,
    wire_nbytes,
)
from twtml_tpu.features.featurizer import Featurizer
from twtml_tpu.models import StreamingLinearRegressionWithSGD
from twtml_tpu.streaming.sources import SyntheticSource


def ragged_batches(n=4, rows=16, unit_bucket=512):
    """n same-signature ragged batches (one compiled program's worth —
    the SuperBatcher grouping precondition)."""
    statuses = list(
        SyntheticSource(total=n * rows, seed=3, base_ms=1785320000000).produce()
    )
    feat = Featurizer(now_ms=1785320000000)
    return [
        feat.featurize_batch_ragged(
            statuses[i * rows : (i + 1) * rows], row_bucket=rows,
            unit_bucket=unit_bucket, pre_filtered=True,
        )
        for i in range(n)
    ]


def wide_ragged_batch(rows=8, row_len=32, seed=5):
    """Hand-built NON-ASCII (uint16 units) ragged batch — the wide-units
    wire composed with the narrow-offsets wire."""
    rng = np.random.default_rng(seed)
    lens = rng.integers(2, row_len, size=rows)
    offsets = np.zeros(rows + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    units = rng.integers(0x100, 0x3FF, size=int(lens.sum())).astype(np.uint16)
    flat, offs = ragged_wire_arrays(units, offsets, rows, rows, narrow=False)
    return RaggedUnitBatch(
        flat, offs,
        rng.normal(size=(rows, 4)).astype(np.float32),
        rng.uniform(0, 100, size=(rows,)).astype(np.float32),
        np.ones((rows,), np.float32),
        row_len=row_len,
    )


def long_row_batch(rows=4, long_len=OFFSET_DELTA_MAX + 2):
    """One row longer than the uint16 delta range: the static row_len
    bucket exceeds 65,535, so the metadata gate keeps the int32 offsets."""
    from twtml_tpu.features.batch import _bucket

    rng = np.random.default_rng(7)
    lens = np.array([8, long_len, 4, 6][:rows])
    offsets = np.zeros(rows + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    units = rng.integers(97, 123, size=int(lens.sum())).astype(np.uint8)
    flat, offs = ragged_wire_arrays(units, offsets, rows, rows, narrow=True)
    return RaggedUnitBatch(
        flat, offs,
        rng.normal(size=(rows, 4)).astype(np.float32),
        rng.uniform(0, 100, size=(rows,)).astype(np.float32),
        np.ones((rows,), np.float32),
        row_len=_bucket(long_len),
    )


# -- coalesced group wire: differential vs the shipped paths -----------------

@pytest.mark.parametrize("k", [1, 4, 8])
def test_group_wire_matches_sequential_single_device(k):
    batches = ragged_batches(n=k)
    seq = StreamingLinearRegressionWithSGD(num_iterations=5)
    outs = [seq.step(pack_batch(b)) for b in batches]  # the shipped k=1 wire

    sup = StreamingLinearRegressionWithSGD(num_iterations=5)
    many = sup.step_many(pack_ragged_group(batches))
    np.testing.assert_array_equal(sup.latest_weights, seq.latest_weights)
    for i, out in enumerate(outs):
        assert float(many.mse[i]) == float(out.mse)
        assert float(many.count[i]) == float(out.count)
        np.testing.assert_array_equal(
            np.asarray(many.predictions[i]), np.asarray(out.predictions)
        )

    # and vs the stacked superbatch wire (the pre-v2 grouping layout)
    stk = StreamingLinearRegressionWithSGD(num_iterations=5)
    stk.step_many(stack_batches(batches))
    np.testing.assert_array_equal(stk.latest_weights, sup.latest_weights)


@pytest.mark.parametrize("k", [1, 4, 8])
def test_group_wire_matches_sequential_mesh(k):
    from twtml_tpu.parallel import ParallelSGDModel, make_mesh
    from twtml_tpu.parallel.sharding import shard_batch

    batches = ragged_batches(n=k, rows=32)
    mesh = make_mesh(num_data=4, devices=jax.devices()[:4])
    seq = ParallelSGDModel(mesh, num_iterations=5, step_size=0.05)
    outs = [seq.step(shard_batch(b, mesh)) for b in batches]

    sup = ParallelSGDModel(mesh, num_iterations=5, step_size=0.05)
    many = sup.step_many(sup.pack_group_for_wire(batches))
    np.testing.assert_array_equal(sup.latest_weights, seq.latest_weights)
    for i, out in enumerate(outs):
        assert float(many.mse[i]) == float(out.mse)
        np.testing.assert_array_equal(
            np.asarray(many.predictions[i]), np.asarray(out.predictions)
        )


def test_group_wire_2d_mesh_matches_sequential():
    from twtml_tpu.parallel import ParallelSGDModel, make_mesh
    from twtml_tpu.parallel.sharding import shard_batch

    batches = ragged_batches(n=4, rows=32)
    mesh = make_mesh(num_data=2, num_model=2, devices=jax.devices()[:4])
    seq = ParallelSGDModel(mesh, num_iterations=5, step_size=0.05)
    outs = [seq.step(shard_batch(b, mesh)) for b in batches]
    sup = ParallelSGDModel(mesh, num_iterations=5, step_size=0.05)
    many = sup.step_many(sup.pack_group_for_wire(batches))
    np.testing.assert_array_equal(sup.latest_weights, seq.latest_weights)
    for i, out in enumerate(outs):
        assert float(many.mse[i]) == float(out.mse)


def test_group_wire_wide_units():
    """Non-ASCII (uint16) units compose with the group wire and the narrow
    offset wire — features bit-identical to plain sequential steps."""
    batches = [wide_ragged_batch(seed=s) for s in (5, 6, 7, 8)]
    pg = pack_ragged_group(batches)
    assert pg.layout[2][3] == "u16delta"  # narrow offsets despite wide units
    back = unpack_batch(pg.buffer, pg.layout)
    for f in ("units", "offsets", "numeric", "label", "mask"):
        np.testing.assert_array_equal(
            np.asarray(getattr(back, f)),
            np.asarray(getattr(stack_batches(batches), f)),
        )
    seq = StreamingLinearRegressionWithSGD(num_iterations=5)
    outs = [seq.step(b) for b in batches]
    sup = StreamingLinearRegressionWithSGD(num_iterations=5)
    many = sup.step_many(pg)
    np.testing.assert_array_equal(sup.latest_weights, seq.latest_weights)
    for i, out in enumerate(outs):
        assert float(many.mse[i]) == float(out.mse)


def test_superbatcher_group_mode_matches_stacked_end_to_end():
    """The app grouping path with --wirePack group: identical per-batch
    stats and final weights as stacked mode, partial tail included (the
    tail rides the k=1 one-buffer wire)."""
    from twtml_tpu.apps.common import SuperBatcher

    batches = ragged_batches(n=7)

    def run(mode):
        model = StreamingLinearRegressionWithSGD(num_iterations=5)
        seen = []
        sb = SuperBatcher(
            model, 3,
            lambda o, b, t, at_boundary: seen.append(
                (float(o.count), float(o.mse), at_boundary)
            ),
            # counter-driven emit points: at_boundary at a non-final group
            # otherwise races the already-done early-emit probe, and the
            # two arms can draw different winners
            deterministic=True,
            wire_pack=mode,
        )
        for i, b in enumerate(batches):
            sb.on_batch(b, float(i))
        sb.flush()
        return model, seen

    m_group, seen_group = run("group")
    m_stacked, seen_stacked = run("stacked")
    assert seen_group == seen_stacked and len(seen_group) == 7
    np.testing.assert_array_equal(
        m_group.latest_weights, m_stacked.latest_weights
    )


def test_superbatcher_group_mode_traces_wire_pack_mode(tmp_path):
    """--trace + --wirePack group: wire_pack spans carry the mode attribute
    ('group' for full groups, 'single' for the partial tail's k=1 pack)."""
    from tools import trace_report
    from twtml_tpu.apps.common import SuperBatcher
    from twtml_tpu.telemetry import trace

    batches = ragged_batches(n=5)
    path = str(tmp_path / "wire.trace")
    trace.install(path)
    try:
        model = StreamingLinearRegressionWithSGD(num_iterations=5)
        sb = SuperBatcher(
            model, 4, lambda o, b, t, at_boundary: None, wire_pack="group"
        )
        for i, b in enumerate(batches):
            sb.on_batch(b, float(i))
        sb.flush()
    finally:
        trace.uninstall()
    spans = [
        e for e in trace_report.load_events(path)
        if e.get("ph") == "X" and e["name"] == "wire_pack"
    ]
    modes = [s["args"]["mode"] for s in spans]
    assert modes.count("group") == 1  # one full group of 4
    assert modes.count("single") == 1  # the one-batch partial tail
    group_span = next(s for s in spans if s["args"]["mode"] == "group")
    assert group_span["args"]["batches"] == 4
    assert group_span["args"]["wire_bytes"] > 0


# -- narrow offset wire: encode gate + fallback ------------------------------

def test_narrow_offset_wire_flat_bit_identical():
    rb = ragged_batches(n=1)[0]
    narrow = pack_batch(rb)  # auto: row_len ≤ 65,535 → u16delta
    wide = pack_batch(rb, narrow_offsets=False)
    assert narrow.layout[2][2] == "u16delta"
    assert wide.layout[2][2] == "i32"
    assert narrow.buffer.nbytes < wide.buffer.nbytes
    for pk in (narrow, wide):
        back = unpack_batch(pk.buffer, pk.layout)
        for f in ("units", "offsets", "numeric", "label", "mask"):
            np.testing.assert_array_equal(
                np.asarray(getattr(back, f)), np.asarray(getattr(rb, f))
            )
        assert back.offsets.dtype == np.int32
    # and through the jit step: bitwise-identical outputs either way
    m_n = StreamingLinearRegressionWithSGD(num_iterations=5)
    m_w = StreamingLinearRegressionWithSGD(num_iterations=5)
    out_n, out_w = m_n.step(narrow), m_w.step(wide)
    for fa, fb in zip(out_n, out_w):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))
    np.testing.assert_array_equal(m_n.latest_weights, m_w.latest_weights)


def test_narrow_offset_wire_sharded_bit_identical():
    from twtml_tpu.features.batch import align_ragged_shards

    rb = ragged_batches(n=1, rows=32)[0]
    aligned = align_ragged_shards(rb, 4)
    for mode, marker in ((None, "u16delta"), (False, "i32")):
        pk = (
            pack_ragged_sharded(aligned)
            if mode is None
            else pack_ragged_sharded(aligned, narrow_offsets=False)
        )
        assert pk.layout[2][2] == marker
        back = unpack_batch(pk.buffer, pk.layout)
        for f in ("units", "offsets", "numeric", "label", "mask"):
            np.testing.assert_array_equal(
                np.asarray(getattr(back, f)), np.asarray(getattr(aligned, f))
            )


def test_long_row_trips_int32_fallback():
    """A row longer than 65,535 units pushes the static row_len bucket past
    the uint16 delta range: the metadata gate keeps the int32 offsets (no
    silent wrap), and the wire still trains bit-identically."""
    rb = long_row_batch()
    assert not offsets_narrow(rb.row_len)
    pk = pack_batch(rb)
    assert pk.layout[2][2] == "i32"  # the auto gate chose the fallback
    # forcing the narrow wire on an out-of-range batch raises, never wraps
    with pytest.raises(ValueError, match="uint16-delta"):
        pack_batch(rb, narrow_offsets=True)
    with pytest.raises(ValueError, match="uint16-delta"):
        pack_ragged_group([rb], narrow_offsets=True)
    # group wire inherits the fallback from the same gate
    pg = pack_ragged_group([rb])
    assert pg.layout[2][3] == "i32"
    back = unpack_batch(pk.buffer, pk.layout)
    for f in ("units", "offsets", "numeric", "label", "mask"):
        np.testing.assert_array_equal(
            np.asarray(getattr(back, f)), np.asarray(getattr(rb, f))
        )
    m_plain = StreamingLinearRegressionWithSGD(num_iterations=3)
    m_pack = StreamingLinearRegressionWithSGD(num_iterations=3)
    out_a, out_b = m_plain.step(rb), m_pack.step(pk)
    for fa, fb in zip(out_a, out_b):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))
    np.testing.assert_array_equal(
        m_plain.latest_weights, m_pack.latest_weights
    )


def test_group_pack_rejects_mixed_signatures():
    a = ragged_batches(n=1, rows=16)[0]
    b = ragged_batches(n=1, rows=32)[0]
    with pytest.raises(ValueError, match="share one wire signature"):
        pack_ragged_group([a, b])
    with pytest.raises(ValueError, match="empty group"):
        pack_ragged_group([])


# -- wire composition metrics (satellite) ------------------------------------

def test_wire_composition_sums_to_wire_nbytes():
    rb = ragged_batches(n=1, rows=32)[0]
    from twtml_tpu.features.batch import align_ragged_shards

    forms = [
        rb,
        pack_batch(rb),
        pack_ragged_sharded(align_ragged_shards(rb, 4)),
        pack_ragged_group(ragged_batches(n=4, rows=32)),
    ]
    for batch in forms:
        comp = wire_composition(batch)
        assert set(comp) == {"units", "offsets", "sideband"}
        assert sum(comp.values()) == wire_nbytes(batch)
    # the narrow wire's offsets are measurably smaller than the int32 wire
    narrow = wire_composition(pack_batch(rb))["offsets"]
    wide = wire_composition(pack_batch(rb, narrow_offsets=False))["offsets"]
    assert narrow < wide


def test_record_metrics_sets_wire_split_gauges():
    from twtml_tpu.streaming.context import FeatureStream
    from twtml_tpu.telemetry import metrics as _metrics

    _metrics.reset_for_tests()
    try:
        rb = ragged_batches(n=1)[0]
        FeatureStream._record_metrics(rb)
        snap = _metrics.get_registry().snapshot()
        comp = wire_composition(rb)
        assert snap["gauges"]["wire.units_bytes"] == comp["units"]
        assert snap["gauges"]["wire.offsets_bytes"] == comp["offsets"]
        assert snap["gauges"]["wire.sideband_bytes"] == comp["sideband"]
        assert snap["counters"]["wire.bytes"] == wire_nbytes(rb)
    finally:
        _metrics.reset_for_tests()
