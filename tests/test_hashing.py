"""Hash parity tests: java_string_hashcode must equal JVM String.hashCode
(the basis of MLlib HashingTF indexing, MllibHelper.scala:18,54).
Expected values are literals computed on a JVM."""

from twtml_tpu.features.hashing import (
    char_bigrams,
    hashing_tf_counts,
    java_string_hashcode,
    non_negative_mod,
)


def test_known_java_hashcodes():
    assert java_string_hashcode("") == 0
    assert java_string_hashcode("a") == 97
    assert java_string_hashcode("ab") == 3105
    assert java_string_hashcode("he") == 3325
    assert java_string_hashcode("hello") == 99162322
    # The canonical overflow example: known JVM value (Integer.MIN_VALUE).
    assert java_string_hashcode("polygenelubricants") == -2147483648


def test_surrogate_pair_hashing():
    # U+1F600 encodes as surrogates D83D DE00 on the JVM:
    # h = 0xD83D * 31 + 0xDE00 = 1772899
    assert java_string_hashcode("\U0001f600") == 1772899


def test_negative_hash_maps_nonnegative():
    h = java_string_hashcode("polygenelubricants")  # == Integer.MIN_VALUE
    assert h < 0
    idx = non_negative_mod(h, 1000)
    assert 0 <= idx < 1000
    # Java: ((-2147483648 % 1000) + 1000) % 1000 == 352
    assert idx == 352


def test_char_bigrams_sliding_semantics():
    # Scala "abcd".sliding(2) -> ab, bc, cd
    assert char_bigrams("abcd") == ["ab", "bc", "cd"]
    # Shorter-than-window strings yield themselves (Scala sliding behavior).
    assert char_bigrams("a") == ["a"]
    assert char_bigrams("") == []


def test_hashing_tf_counts_accumulate():
    counts = hashing_tf_counts(["ab", "ab", "he"], 1000)
    assert counts[3105 % 1000] == 2.0
    assert counts[3325 % 1000] == 1.0


def test_collisions_accumulate():
    # Two distinct terms forced onto the same index with tiny mod.
    counts = hashing_tf_counts(["a", "b"], 1)
    assert counts == {0: 2.0}
