"""Replica launcher for the fleet integration tests: configures a 1-device
CPU jax runtime, then drives the REAL serve entry point with its own CLI —
one ``apps/serve.py`` replica process of a read fleet
(``--checkpointDir`` shared with the trainer and the other replicas).

Not a test module — spawned by tests/test_fleet.py.

Usage: python tests/serve_worker.py [serve args...]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from twtml_tpu.utils.backend import set_cpu_device_count_hint  # noqa: E402

set_cpu_device_count_hint(1)

from twtml_tpu.apps import serve  # noqa: E402

serve.main(list(sys.argv[1:]))
