"""One-batch-lag telemetry fetch (apps/common.LagPipeline): back-to-back
apps emit batch k−1's stats just before dispatching batch k, so the stats
round trip overlaps the next batch's work. The pipeline must preserve the
synchronous path's semantics exactly: every batch handled once, in order,
weights current at handle time (at_boundary=True), max-batches stops
vetoing further dispatches, and the final batch drained by flush()."""

import json
import os

import numpy as np

from twtml_tpu.apps.common import LagPipeline
from twtml_tpu.config import ConfArguments
from twtml_tpu.streaming.sources import SyntheticSource

DATA = os.path.join(os.path.dirname(__file__), "data", "tweets.jsonl")


class FakeModel:
    def __init__(self):
        self.dispatched = []

    def step(self, batch):
        self.dispatched.append(batch)
        return {"i": np.asarray(batch)}


def test_emits_in_order_with_one_batch_lag_and_flush():
    model, events = FakeModel(), []
    pipe = LagPipeline(
        model, lambda out, b, t, at_boundary: events.append((int(out["i"]), at_boundary))
    )
    for i in range(4):
        pipe.on_batch(i, 0.0)
        # batch i dispatched, batch i-1 handled: exactly one batch of lag
        assert model.dispatched == list(range(i + 1))
        assert events == [(j, True) for j in range(i)]
    pipe.flush()
    assert events == [(j, True) for j in range(4)]
    pipe.flush()  # idempotent
    assert len(events) == 4


def test_stop_requested_vetoes_the_next_dispatch():
    model, events = FakeModel(), []
    stop = {"flag": False}

    def handle(out, b, t, at_boundary):
        events.append(int(out["i"]))
        if out["i"] >= 1:
            stop["flag"] = True  # cap reached at batch 1

    pipe = LagPipeline(model, handle, stop_requested=lambda: stop["flag"])
    for i in range(5):
        pipe.on_batch(i, 0.0)
    pipe.flush()
    # batch 2 arrived after handle(1) set the stop: it must not dispatch,
    # and later batches must not either
    assert model.dispatched == [0, 1]
    assert events == [0, 1]


def test_linear_app_max_batches_exact_under_lag(tmp_path):
    """The flagship app in back-to-back mode (--seconds 0, where the lag
    pipeline engages) trains EXACTLY max_batches batches, as the inline
    fetch did."""
    import jax

    from tools.bench_suite import _status_json
    from twtml_tpu.apps import linear_regression as app

    jax.devices()  # lock the conftest's 8-device backend before local[1]

    path = tmp_path / "tweets.jsonl"
    statuses = list(
        SyntheticSource(total=8 * 16, seed=11, base_ms=1785320000000).produce()
    )
    with open(path, "w") as fh:
        for s in statuses:
            fh.write(json.dumps(_status_json(s)) + "\n")

    conf = ConfArguments().parse([
        "--source", "replay", "--replayFile", str(path),
        "--seconds", "0", "--backend", "cpu",
        "--batchBucket", "16", "--tokenBucket", "64",
        "--master", "local[1]",
    ])
    totals = app.run(conf, max_batches=3)
    assert totals["batches"] == 3
    assert totals["count"] == 3 * 16
