"""Unit tests for the elastic membership protocol machine (r16, ISSUE 13).

The plane is driven here as PURE protocol — synthetic gathered matrices in,
action strings out; no jax, no sockets, no processes. The wire-level truth
(real gloo groups shrinking and re-growing) lives in
tests/test_elastic_multiprocess.py.
"""

from __future__ import annotations

import numpy as np
import pytest

from twtml_tpu.parallel.elastic import mask_from_uids, uids_from_mask
from twtml_tpu.streaming import membership as ms
from twtml_tpu.telemetry import sideband as _sideband


class _StubRuntime:
    """Duck-typed ElasticRuntime: the plane reads uid/epoch/members and the
    (absent) beacon; the attach callback mutates epoch/members like a real
    re-formation would."""

    def __init__(self, uid: int, epoch: int = 0, members=(0, 1, 2)):
        self.uid = uid
        self.epoch = epoch
        self.members = list(members)
        self.beacon = None
        self.lead_uid = 0

    def set_lead(self, uid: int) -> None:
        self.lead_uid = int(uid)


def _plane(uid, transitions, members=(0, 1, 2), **kw):
    rt = _StubRuntime(uid, members=members)

    def detach(clean):
        transitions.append((uid, "detach", clean))

    def attach(plan, reason):
        transitions.append((uid, "attach", plan["epoch"], reason))
        rt.epoch = plan["epoch"]
        rt.members = list(plan["members"])

    return ms.MembershipPlane(rt, detach, attach, **kw)


def teardown_function(_fn):
    _sideband.reset_for_tests()


def test_view_mask_roundtrip_and_ceiling():
    assert uids_from_mask(mask_from_uids([0, 1, 5])) == [0, 1, 5]
    assert uids_from_mask(0) == []
    assert mask_from_uids([]) == 0
    # float64 int-exactness bounds the encoding at 52 hosts
    with pytest.raises(ValueError):
        mask_from_uids([52])


def test_steady_state_columns_are_inert():
    transitions: list = []
    planes = [_plane(u, transitions) for u in range(3)]
    rows = np.stack([p.pre_tick() for p in planes]).astype(np.int64)
    # no proposal anywhere: every ingest is a no-op on every host
    for p in planes:
        assert p.ingest(rows) == ""
    assert transitions == []
    # the published columns carry the agreed view
    for u, p in enumerate(planes):
        col = p.pre_tick()
        assert int(col[ms.FIELDS.index("uid")]) == u
        assert int(col[ms.FIELDS.index("view")]) == mask_from_uids([0, 1, 2])
        assert int(col[ms.FIELDS.index("prop_epoch")]) == 0


def test_straggler_eviction_two_tick_dance_commits_simultaneously():
    """The full in-band protocol: the sideband names host 1 (pid 1) as
    persistently gating → the lead proposes at tick T, every member acks
    at T+1, and the SAME gathered matrix makes every survivor reform and
    the evictee park."""
    transitions: list = []
    planes = [
        _plane(u, transitions, evict_ticks=2, evict_skew_ms=100.0)
        for u in range(3)
    ]
    _sideband.publish_hosts(
        {"hosts": [], "straggler": 1, "stage": "upload", "skew_ms": 400.0}
    )
    # tick 1: first gating observation — below the 2-tick bar, no proposal
    rows = np.stack([p.pre_tick() for p in planes]).astype(np.int64)
    assert int(rows[0, ms.FIELDS.index("prop_epoch")]) == 0
    for p in planes:
        assert p.ingest(rows) == ""
    # tick 2: second consecutive observation — the lead proposes epoch 1
    # without uid 1 and trivially acks its own proposal
    rows = np.stack([p.pre_tick() for p in planes]).astype(np.int64)
    assert int(rows[0, ms.FIELDS.index("prop_epoch")]) == 1
    assert uids_from_mask(int(rows[0, ms.FIELDS.index("prop_view")])) == [0, 2]
    assert int(rows[0, ms.FIELDS.index("ack")]) == 1
    # followers see it in this gather; they ack from the NEXT tick
    for p in planes:
        assert p.ingest(rows) == ""
    # tick 3: every row acks → commit, evaluated identically everywhere
    rows = np.stack([p.pre_tick() for p in planes]).astype(np.int64)
    assert (rows[:, ms.FIELDS.index("ack")] == 1).all()
    actions = [p.ingest(rows) for p in planes]
    assert actions == ["reform", "parked", "reform"]
    # survivors execute the committed plan (detach clean, attach epoch 1)
    for p in (planes[0], planes[2]):
        p.execute_reform()
    assert (0, "detach", True) in transitions
    assert (0, "attach", 1, "evict") in transitions
    assert (2, "attach", 1, "evict") in transitions
    assert planes[0].members == [0, 2]


def test_lead_is_never_self_evicted():
    transitions: list = []
    lead = _plane(0, transitions, evict_ticks=1, evict_skew_ms=100.0)
    _sideband.publish_hosts(
        {"hosts": [], "straggler": 0, "stage": "fetch", "skew_ms": 900.0}
    )
    rows = lead.pre_tick()[None, :].astype(np.int64)
    assert int(rows[0, ms.FIELDS.index("prop_epoch")]) == 0
    assert lead.ingest(rows) == ""


def test_eviction_requires_consecutive_ticks():
    transitions: list = []
    lead = _plane(0, transitions, evict_ticks=3, evict_skew_ms=100.0)
    for straggler in (1, 2, 1):  # alternating hosts reset the run
        _sideband.publish_hosts(
            {"hosts": [], "straggler": straggler, "stage": "upload",
             "skew_ms": 500.0}
        )
        cols = lead.pre_tick()
        assert int(cols[ms.FIELDS.index("prop_epoch")]) == 0


def test_low_skew_never_proposes():
    transitions: list = []
    lead = _plane(0, transitions, evict_ticks=1, evict_skew_ms=250.0)
    _sideband.publish_hosts(
        {"hosts": [], "straggler": 1, "stage": "upload", "skew_ms": 50.0}
    )
    cols = lead.pre_tick()
    assert int(cols[ms.FIELDS.index("prop_epoch")]) == 0


# ---------------------------------------------------------------------------
# lead election (r20, ISSUE 17): pure protocol — no sockets, no processes


def test_election_candidates_successor_ordering():
    # successor rule: lowest live uid in the committed view, lead excluded
    assert ms.election_candidates([0, 1, 2, 3], 0) == [1, 2, 3]
    # membership order doesn't matter; uid order decides the ranks
    assert ms.election_candidates([4, 2, 7], 2) == [4, 7]
    # simultaneous lead + successor death: rank 0 (uid 1) never answers,
    # rank 1 (uid 2) wins the bind after its stagger — the ordering alone
    # makes the outcome deterministic without any extra agreement
    assert ms.election_candidates([0, 1, 2], 0) == [1, 2]
    # lead already gone from the view (evicted earlier): nothing to exclude
    assert ms.election_candidates([3, 5], 0) == [3, 5]


def test_ex_lead_rejoin_is_demoted_to_follower():
    # a restarted uid 0 joining a fleet led by an elected successor must
    # come back as a follower: leadership is sticky to lead_uid, not to
    # the uid-0 birthright
    rt = _StubRuntime(0)
    rt.lead_uid = 2
    plane = ms.MembershipPlane(
        rt, lambda clean: None, lambda plan, reason: None
    )
    assert plane.lead_uid == 2
    assert not plane.lead


def test_adopt_lead_handoff_updates_runtime_and_counts():
    from twtml_tpu.telemetry import metrics as _metrics

    plane = _plane(2, [])
    snap = _metrics.get_registry().snapshot()
    before = snap["counters"].get("elastic.lead_handoffs", 0)
    plane._adopt_lead({"lead_uid": 1}, "wedge report")
    assert plane.runtime.lead_uid == 1 and plane.lead_uid == 1
    assert not plane.lead
    plane._adopt_lead({"lead_uid": 2}, "admission plan")
    assert plane.lead  # this host IS uid 2: adopted leadership
    # missing / unchanged lead_uid is a no-op
    plane._adopt_lead({}, "hello")
    plane._adopt_lead({"lead_uid": 2}, "hello")
    assert plane.lead_uid == 2
    snap = _metrics.get_registry().snapshot()
    assert snap["counters"].get("elastic.lead_handoffs", 0) - before == 2
    assert snap["gauges"].get("elastic.lead_uid") == 2


def test_ingest_reads_proposal_from_elected_lead_row():
    """After a handoff to uid 1, every host reads the proposal columns
    from the ELECTED lead's row — the full evict dance driven by a
    non-zero lead, bit-for-bit like the uid-0 version above."""
    transitions: list = []
    planes = [
        _plane(u, transitions, evict_ticks=1, evict_skew_ms=100.0)
        for u in range(3)
    ]
    for p in planes:
        p.runtime.set_lead(1)
    _sideband.publish_hosts(
        {"hosts": [], "straggler": 2, "stage": "upload", "skew_ms": 500.0}
    )
    rows = np.stack([p.pre_tick() for p in planes]).astype(np.int64)
    # only the elected lead proposes; the ex-lead row stays quiet
    assert int(rows[1, ms.FIELDS.index("prop_epoch")]) == 1
    assert int(rows[0, ms.FIELDS.index("prop_epoch")]) == 0
    assert uids_from_mask(int(rows[1, ms.FIELDS.index("prop_view")])) == [0, 1]
    for p in planes:
        assert p.ingest(rows) == ""
    rows = np.stack([p.pre_tick() for p in planes]).astype(np.int64)
    assert (rows[:, ms.FIELDS.index("ack")] == 1).all()
    actions = [p.ingest(rows) for p in planes]
    assert actions == ["reform", "reform", "parked"]


def test_elected_lead_is_never_self_evicted():
    transitions: list = []
    plane = _plane(1, transitions, evict_ticks=1, evict_skew_ms=100.0)
    plane.runtime.set_lead(1)
    _sideband.publish_hosts(
        {"hosts": [], "straggler": 1, "stage": "fetch", "skew_ms": 900.0}
    )
    cols = plane.pre_tick()
    assert int(cols[ms.FIELDS.index("prop_epoch")]) == 0


def test_beacon_port_handoff_arithmetic():
    from twtml_tpu.parallel import elastic

    # the beacon lives at base+1 for the LIFETIME of the fleet: a
    # successor re-binds the exact port the dead lead owned (the bind is
    # the election lock), while epoch coordinators advance at base+2+e
    # and never collide with it
    rt = object.__new__(elastic.ElasticRuntime)
    rt.base_port = 9000
    assert rt.beacon_port == 9000 + elastic.BEACON_OFFSET == 9001
    assert rt.port_for(0) == 9002
    assert rt.port_for(5) == 9007
    assert all(rt.port_for(e) != rt.beacon_port for e in range(52))


# ---------------------------------------------------------------------------
# chaos grammar: peer.kill / peer.pause (streaming/faults.py)

from twtml_tpu.streaming.faults import (  # noqa: E402
    PEER_KILL_EXIT_CODE,
    ChaosInjector,
)


def test_peer_chaos_grammar_parses():
    inj = ChaosInjector("peer.kill:tick=7")
    (rule,) = inj._rules["peer.kill"]
    assert rule.kind == "kill" and int(rule.value) == 7
    inj = ChaosInjector("peer.pause:ticks=3@5")
    (rule,) = inj._rules["peer.pause"]
    assert rule.kind == "pause" and int(rule.value) == 3
    assert rule.mode == "every" and int(rule.param) == 5
    # defaults: kill at tick 1; pause for the documented default ticks
    assert int(ChaosInjector("peer.kill")._rules["peer.kill"][0].value) == 1
    assert "tick" in repr(ChaosInjector("peer.kill:tick=2")._rules["peer.kill"][0])


def test_peer_chaos_uid_selector_parses_and_filters():
    # kill-the-lead from one fleet-wide spec: the uid selector names the
    # host by its ORIGINAL process id, order-free with tick=
    inj = ChaosInjector("peer.kill:uid=0:tick=4")
    (rule,) = inj._rules["peer.kill"]
    assert rule.kind == "kill" and int(rule.value) == 4 and rule.uid == 0
    assert rule.on_host(0) and not rule.on_host(3)
    assert "uid=0" in repr(rule)
    inj = ChaosInjector("peer.pause:ticks=2:uid=5@3")
    (rule,) = inj._rules["peer.pause"]
    assert rule.kind == "pause" and int(rule.value) == 2 and rule.uid == 5
    # no selector = every host (the pre-r20 behavior)
    assert ChaosInjector("peer.kill")._rules["peer.kill"][0].on_host(7)


def test_peer_kill_uid_selector_only_fires_on_target(monkeypatch):
    import os as _os

    deaths: list = []
    inj = ChaosInjector("peer.kill:uid=1:tick=2")
    monkeypatch.setattr(_os, "_exit", lambda c: deaths.append(c))
    inj.peer_chaos(2, 0.0, uid=0)   # wrong host: survives
    assert deaths == []
    inj.peer_chaos(2, 0.0, uid=1)   # the named host dies
    assert deaths == [PEER_KILL_EXIT_CODE]


def test_peer_pause_uid_filter_keeps_rng_draws_fleet_identical():
    """uid-selected pause rules must evaluate their RNG draw on EVERY
    host (filtering happens after ``fires``) — otherwise a prob-mode rule
    alongside a uid-selected one would desynchronize the seeded sequence
    across the fleet."""
    import twtml_tpu.streaming.faults as faults

    def draws(uid):
        inj = ChaosInjector(
            "peer.pause:uid=3:ticks=1@p0.5,peer.pause:ticks=1@p0.5,seed=9"
        )
        fired_at = []
        orig_sleep = faults.time.sleep
        faults.time.sleep = lambda s: fired_at.append(s)
        try:
            for tick in range(1, 40):
                inj.peer_chaos(tick, 0.0, uid=uid)
        finally:
            faults.time.sleep = orig_sleep
        return inj._rng.random()  # final RNG state == identical sequence

    assert draws(0) == draws(3) == draws(11)


@pytest.mark.parametrize("bad", [
    "peer.kill:ticks=3",        # kill takes tick=, not ticks=
    "peer.kill:tick=0",
    "peer.pause:tick=3",        # pause takes ticks=
    "peer.pause:ticks=0",
    "peer.kill:delay=2",
])
def test_peer_chaos_grammar_rejects_malformed(bad):
    with pytest.raises(ValueError):
        ChaosInjector(bad)


def test_peer_pause_sleeps_at_its_trigger(monkeypatch):
    import twtml_tpu.streaming.faults as faults

    naps: list = []
    monkeypatch.setattr(faults.time, "sleep", lambda s: naps.append(s))
    inj = ChaosInjector("peer.pause:ticks=4@3")
    for tick in (1, 2):
        inj.peer_chaos(tick, 0.0)
    assert naps == []
    inj.peer_chaos(3, 0.0)
    # back-to-back interval floors at 0.5 s per tick of pause
    assert naps == [pytest.approx(2.0)]


def test_peer_kill_exit_code_is_distinct():
    # 77 collides with neither clean failures (1), SIGABRT (-6/134), nor
    # SIGKILL (-9/137) — the elastic tests key on it
    assert PEER_KILL_EXIT_CODE == 77


def test_config_elastic_flags_parse():
    from twtml_tpu.config import ConfArguments

    conf = ConfArguments().parse([
        "--elastic", "on", "--elasticEvictTicks", "4",
        "--elasticEvictSkewMs", "300", "--elasticRejoin", "off",
    ])
    assert conf.elastic == "on"
    assert conf.elasticEvictTicks == 4
    assert conf.elasticEvictSkewMs == 300.0
    assert conf.elasticRejoin == "off"
    with pytest.raises(SystemExit):
        ConfArguments().parse(["--elastic", "maybe"])
