"""One-pass wire assembly + pooled buffer arena (ISSUE 14, r17).

The fused native emitter (native/wireassemble.cpp via
features/assemble.py) must be BYTE-IDENTICAL — buffer and layout — to the
numpy pack pipeline (features/batch.py, the ground truth) on every wire
form × codec state × fallback, and trained trajectories must be
bitwise-equal with the assembler on vs off. The arena
(features/arena.py) changes who owns the bytes, never the bytes: leases
ride the dispatch pipelines and retire on fetch delivery (discard on
abort), with the accounting asserted here. The stale-library degrade
seam mirrors PR 6's: a real .so without ``wire_assemble`` loads, flags
once, and every pack keeps flowing through numpy.
"""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from twtml_tpu.features import arena as arena_mod  # noqa: E402
from twtml_tpu.features import assemble, native  # noqa: E402
from twtml_tpu.features.batch import (  # noqa: E402
    OFFSET_DELTA_MAX,
    RaggedUnitBatch,
    align_ragged_shards,
    pack_batch,
    pack_ragged_group,
    pack_ragged_sharded,
    ragged_wire_arrays,
    unpack_batch,
)
from twtml_tpu.features.featurizer import Featurizer  # noqa: E402
from twtml_tpu.models import StreamingLinearRegressionWithSGD  # noqa: E402
from twtml_tpu.streaming.sources import SyntheticSource  # noqa: E402

needs_native = pytest.mark.skipif(
    not native.assemble_available(),
    reason="native wire assembler unavailable (no g++?)",
)


# ---------------------------------------------------------------------------
# builders


def hand_batch(
    b=32, seed=1, wide=False, incompressible=False, row_len=96
):
    """Hand-built ragged batch: ASCII tweet-like text by default; ``wide``
    adds one non-ASCII row (the uint16-widened wire); ``incompressible``
    uses uniform random bytes (the codec's raw fallback)."""
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(b - 3):
        n = int(rng.integers(1, row_len))
        if incompressible:
            rows.append(rng.integers(0, 128, n).astype(np.uint16))
        else:
            text = np.frombuffer(
                b"the streaming fox https://t.co/ab jumps again and ",
                np.uint8,
            )
            rows.append(text[np.arange(n) % len(text)].astype(np.uint16))
    if wide and rows:
        rows[0] = np.concatenate(
            [rows[0], np.array([0x3042], np.uint16)]
        )
    units = (
        np.concatenate(rows) if rows else np.zeros(0, np.uint16)
    )
    offsets = np.zeros(len(rows) + 1, np.int64)
    np.cumsum([len(r) for r in rows], out=offsets[1:])
    flat, offs = ragged_wire_arrays(
        units, offsets, len(rows), b, narrow=not wide
    )
    numeric = rng.normal(size=(b, 4)).astype(np.float32)
    label = rng.uniform(0, 50, size=(b,)).astype(np.float32)
    mask = np.zeros(b, np.float32)
    mask[: len(rows)] = 1.0
    return RaggedUnitBatch(
        flat, offs, numeric, label, mask, row_len=row_len
    )


def signature_variants(al, k):
    """k same-signature copies differing only in sideband values."""
    return [
        RaggedUnitBatch(
            al.units.copy(), al.offsets.copy(), al.numeric + j,
            al.label + j, al.mask.copy(),
            row_len=al.row_len, num_shards=al.num_shards,
        )
        for j in range(k)
    ]


def featurized_batches(n=4, rows=16, unit_bucket=512):
    statuses = list(SyntheticSource(
        total=n * rows, seed=3, base_ms=1785320000000
    ).produce())
    feat = Featurizer(now_ms=1785320000000)
    return [
        feat.featurize_batch_ragged(
            statuses[i * rows : (i + 1) * rows], row_bucket=rows,
            unit_bucket=unit_bucket, pre_filtered=True,
        )
        for i in range(n)
    ]


def assert_same_packed(got, ref, tag=""):
    assert got.layout == ref.layout, (tag, got.layout, ref.layout)
    np.testing.assert_array_equal(
        np.asarray(got.buffer), np.asarray(ref.buffer), err_msg=tag
    )


def both_modes(fn):
    with assemble.forced("off"):
        ref = fn()
    with assemble.forced("on"):
        got = fn()
    return got, ref


# ---------------------------------------------------------------------------
# byte parity: every layout × codec × fallback


@needs_native
@pytest.mark.parametrize("codec", [None, "dict"])
@pytest.mark.parametrize("wide", [False, True])
@pytest.mark.parametrize("incompressible", [False, True])
def test_flat_pack_byte_parity(codec, wide, incompressible):
    rb = hand_batch(wide=wide, incompressible=incompressible)
    got, ref = both_modes(lambda: pack_batch(rb, codec=codec))
    assert_same_packed(got, ref, "flat")
    # and the fast path actually ran (not a silent permanent fallback)
    assert got._lease is not None


@needs_native
@pytest.mark.parametrize("codec", [None, "dict"])
@pytest.mark.parametrize("s", [1, 2, 4])
def test_sharded_pack_byte_parity(codec, s):
    al = align_ragged_shards(hand_batch(), s)
    got, ref = both_modes(
        lambda: pack_ragged_sharded(al, codec=codec)
    )
    assert_same_packed(got, ref, f"sharded s={s}")


@needs_native
@pytest.mark.parametrize("codec", [None, "dict"])
@pytest.mark.parametrize("s,k", [(1, 1), (1, 3), (2, 1), (2, 3)])
def test_group_pack_byte_parity(codec, s, k):
    parts = signature_variants(
        align_ragged_shards(hand_batch(), s), k
    )
    got, ref = both_modes(
        lambda: pack_ragged_group(parts, codec=codec)
    )
    assert_same_packed(got, ref, f"group s={s} k={k}")


@needs_native
@pytest.mark.parametrize("narrow", [None, False])
def test_offset_modes_byte_parity(narrow):
    rb = hand_batch()
    got, ref = both_modes(
        lambda: pack_batch(rb, narrow_offsets=narrow)
    )
    assert_same_packed(got, ref, f"narrow={narrow}")
    al = align_ragged_shards(rb, 2)
    got, ref = both_modes(
        lambda: pack_ragged_sharded(al, narrow_offsets=narrow)
    )
    assert_same_packed(got, ref)


@needs_native
def test_featurized_group_byte_parity():
    batches = featurized_batches(n=4)
    got, ref = both_modes(lambda: pack_ragged_group(batches))
    assert_same_packed(got, ref, "featurized group")


@needs_native
def test_long_row_int32_fallback_parity():
    """row_len past the uint16 delta range: the metadata gate keeps the
    int32 offset wire in BOTH paths (auto narrow resolves to off)."""
    from twtml_tpu.features.batch import _bucket

    lens = np.array([8, OFFSET_DELTA_MAX + 2, 4, 6])
    offsets = np.zeros(5, np.int64)
    np.cumsum(lens, out=offsets[1:])
    units = np.random.default_rng(7).integers(
        97, 123, size=int(lens.sum())
    ).astype(np.uint16)
    flat, offs = ragged_wire_arrays(units, offsets, 4, 4, narrow=True)
    rb = RaggedUnitBatch(
        flat, offs,
        np.zeros((4, 4), np.float32), np.zeros(4, np.float32),
        np.ones(4, np.float32), row_len=_bucket(OFFSET_DELTA_MAX + 2),
    )
    got, ref = both_modes(lambda: pack_batch(rb))
    assert got.layout[2][2] == "i32"
    assert_same_packed(got, ref, "long-row i32")
    # forcing the narrow wire past the gate raises in both modes (the
    # native path refuses and routes to the numpy error)
    for mode in ("off", "on"):
        with assemble.forced(mode):
            with pytest.raises(ValueError):
                pack_batch(rb, narrow_offsets=True)


@needs_native
def test_forced_codec_bucket_parity_and_overflow():
    """The multi-host agreed bucket: parity when it covers, the canonical
    ValueError (from the ground truth) when it under-covers — in both
    modes."""
    from twtml_tpu.features.wirecodec import encode, encoded_bucket

    al = align_ragged_shards(hand_batch(), 2)
    segs = np.asarray(al.units).reshape(2, -1)
    max_enc = max(encode(r).shape[0] for r in segs)
    bucket = encoded_bucket(max_enc) + 1024
    got, ref = both_modes(
        lambda: pack_ragged_sharded(
            al, codec="dict", codec_bucket=bucket
        )
    )
    assert_same_packed(got, ref, "forced bucket")
    if max_enc > 1:
        under = max(1, max_enc - 1)
        for mode in ("off", "on"):
            with assemble.forced(mode):
                with pytest.raises(ValueError):
                    pack_ragged_sharded(
                        al, codec="dict", codec_bucket=under
                    )


@needs_native
def test_unpack_round_trip_host_and_jit():
    import jax

    parts = signature_variants(
        align_ragged_shards(hand_batch(), 1), 3
    )
    with assemble.forced("on"):
        pb = pack_ragged_group(parts, codec="dict")
    host = unpack_batch(pb.buffer, pb.layout)
    with assemble.forced("off"):
        ref = unpack_batch(
            pack_ragged_group(parts, codec="dict").buffer, pb.layout
        )
    for f in ("units", "offsets", "numeric", "label", "mask"):
        np.testing.assert_array_equal(
            np.asarray(getattr(host, f)), np.asarray(getattr(ref, f))
        )
    dev = jax.jit(lambda buf: unpack_batch(buf, pb.layout).units)(
        pb.buffer
    )
    np.testing.assert_array_equal(np.asarray(dev), np.asarray(host.units))


# ---------------------------------------------------------------------------
# trajectory parity: assembler on vs off trains bitwise-equal weights


@needs_native
def test_trajectory_bitwise_single_device():
    batches = featurized_batches(n=6)
    finals = {}
    for mode in ("off", "on"):
        with assemble.forced(mode):
            m = StreamingLinearRegressionWithSGD(num_iterations=5)
            for b in batches:
                m.step(pack_batch(b))
            finals[mode] = np.asarray(m.latest_weights)
    np.testing.assert_array_equal(finals["off"], finals["on"])


@needs_native
def test_trajectory_bitwise_mesh():
    import jax

    from twtml_tpu.parallel import ParallelSGDModel, make_mesh

    batches = featurized_batches(n=4, rows=32)
    finals = {}
    for mode in ("off", "on"):
        with assemble.forced(mode):
            mesh = make_mesh(num_data=4, devices=jax.devices()[:4])
            m = ParallelSGDModel(mesh, num_iterations=5, step_size=0.05)
            for b in batches:
                m.step(m.pack_for_wire(b))
            finals[mode] = np.asarray(m.latest_weights)
    np.testing.assert_array_equal(finals["off"], finals["on"])


@needs_native
def test_trajectory_bitwise_tenant_stack():
    from twtml_tpu.parallel import TenantStackModel

    batches = featurized_batches(n=4, rows=32)
    finals = {}
    for mode in ("off", "on"):
        with assemble.forced(mode):
            mt = TenantStackModel(
                3, num_iterations=5, step_size=0.1, wire_pack="group"
            )
            for b in batches:
                mt.step(b)
            finals[mode] = np.asarray(mt.latest_weights)
    np.testing.assert_array_equal(finals["off"], finals["on"])


# ---------------------------------------------------------------------------
# arena accounting


def test_arena_lease_retire_recycles():
    a = arena_mod.WireArena()
    l1 = a.lease(4096)
    buf1 = l1.buf
    assert a.stats()["in_use"] == 1
    l1.retire()
    assert a.stats() == {
        "in_use": 0, "free_buffers": 1, "free_bytes": 4096,
    }
    l2 = a.lease(4096)
    assert l2.buf is buf1  # recycled, not reallocated
    # retire is idempotent
    l2.retire()
    l2.retire()
    assert a.stats()["in_use"] == 0
    assert a.stats()["free_buffers"] == 1


def test_arena_discard_never_recycles():
    a = arena_mod.WireArena()
    le = a.lease(2048)
    le.discard()
    assert a.stats() == {
        "in_use": 0, "free_buffers": 0, "free_bytes": 0,
    }


def test_arena_pool_cap_bounds_free_bytes():
    a = arena_mod.WireArena(max_pool_bytes=8192)
    leases = [a.lease(4096) for _ in range(4)]
    for le in leases:
        le.retire()
    assert a.stats()["free_bytes"] <= 8192


def test_arena_disabled_is_fresh_alloc_control():
    a = arena_mod.WireArena()
    a.enabled = False
    le = a.lease(1024)
    le.retire()
    assert a.stats()["free_buffers"] == 0  # nothing pooled
    l2 = a.lease(1024)
    assert l2.buf is not le.buf


def test_pack_attaches_lease_and_counts():
    from twtml_tpu.telemetry import metrics as _metrics

    arena_mod.get_arena().reset_for_tests()
    reg = _metrics.get_registry()
    before = reg.counter("wire.arena_misses").snapshot()
    rb = hand_batch()
    pb = pack_batch(rb)
    assert pb._lease is not None
    assert pb._lease.buf.nbytes >= pb.buffer.nbytes
    assert reg.counter("wire.arena_misses").snapshot() > before
    pb._lease.retire()
    pb2 = pack_batch(rb)
    # identical signature → the retired buffer is the recycled one
    assert pb2._lease.buf is pb._lease.buf
    pb2._lease.retire()


# ---------------------------------------------------------------------------
# pipeline integration: leases retire on delivery, discard on abort


class _EchoModel:
    """Step = identity-ish dispatch; fetch-side device_get of plain numpy
    is a no-op — enough to drive the pipelines' accounting."""

    accepts_packed = True

    def step(self, wire):
        return {"mse": np.float32(1.0)}


def _ragged_stream(n=5):
    return [hand_batch(seed=10 + i) for i in range(n)]


def test_fetch_pipeline_retires_leases_on_delivery():
    from twtml_tpu.apps.common import FetchPipeline

    arena_mod.get_arena().reset_for_tests()
    got = []
    pipe = FetchPipeline(
        _EchoModel(), lambda out, b, t, at_boundary: got.append(out),
        depth=3, pack=True,
    )
    for i, b in enumerate(_ragged_stream()):
        pipe.on_batch(b, float(i))
    pipe.flush()
    assert len(got) == 5
    st = arena_mod.get_arena().stats()
    assert st["in_use"] == 0  # every lease retired on delivery
    assert st["free_buffers"] >= 1  # and recycled through the pool


def test_fetch_pipeline_discards_leases_on_abort(monkeypatch):
    from twtml_tpu.apps.common import FetchAbort, FetchPipeline

    arena_mod.get_arena().reset_for_tests()
    # deterministic: no opportunistic early emit — all three stay pending
    pipe = FetchPipeline(
        _EchoModel(), lambda *a, **k: None, depth=8, pack=True,
        deterministic=True,
    )
    for i, b in enumerate(_ragged_stream(3)):
        pipe.on_batch(b, float(i))
    assert arena_mod.get_arena().stats()["in_use"] == 3

    def boom(future, reissue):
        raise FetchAbort("wedged")

    monkeypatch.setattr(pipe._watchdog, "await_result", boom)
    pipe.flush()  # drops pending outputs, discards (never pools) leases
    st = arena_mod.get_arena().stats()
    assert st["in_use"] == 0
    assert st["free_buffers"] == 0  # abort path: no reuse


def test_super_batcher_group_leases_retire():
    from twtml_tpu.apps.common import SuperBatcher

    class _GroupModel(_EchoModel):
        def step_many(self, wire):
            return {"mse": np.zeros(4, np.float32)}

    arena_mod.get_arena().reset_for_tests()
    got = []
    from twtml_tpu.models.base import StepOutput

    n_fields = len(StepOutput._fields)

    def handle(out, batch, t, at_boundary):
        got.append(t)

    batcher = SuperBatcher(
        _GroupModel(), 4,
        handle, wire_pack="group",
    )
    al = align_ragged_shards(hand_batch(), 1)
    # step_many's fake output must be StepOutput-shaped for re-emit
    def step_many(wire):
        return StepOutput(*(
            np.zeros((4,), np.float32) for _ in range(n_fields)
        ))

    batcher.model.step_many = step_many
    for j, b in enumerate(signature_variants(al, 8)):
        batcher.on_batch(b, float(j))
    batcher.flush()
    assert len(got) == 8
    st = arena_mod.get_arena().stats()
    assert st["in_use"] == 0
    assert st["free_buffers"] >= 1


# ---------------------------------------------------------------------------
# the stale-library degrade seam


def test_bind_assemble_flags_missing_symbol_and_counts(monkeypatch):
    from twtml_tpu.telemetry import metrics as _metrics

    class _NoAssemble:
        def __getattr__(self, name):
            raise AttributeError(name)

    _metrics.reset_for_tests()
    monkeypatch.setattr(native, "_assemble_missing", False)
    with pytest.raises(AttributeError):
        native._bind_assemble(_NoAssemble(), strict=True)
    native._bind_assemble(_NoAssemble(), strict=False)
    assert native._assemble_missing
    assert _metrics.get_registry().counter(
        "native.assemble_degraded"
    ).snapshot() == 1
    monkeypatch.setattr(native, "_assemble_missing", False)


def test_assemble_missing_degrades_to_numpy(monkeypatch):
    monkeypatch.setattr(native, "_assemble_missing", True)
    assert not native.assemble_available()
    assert not assemble.available()
    rb = hand_batch()
    with assemble.forced("on"):  # even explicit on degrades, never dies
        pb = pack_batch(rb)
    monkeypatch.setattr(native, "_assemble_missing", False)
    with assemble.forced("off"):
        ref = pack_batch(rb)
    assert_same_packed(pb, ref, "degraded")


def test_stale_library_without_assemble_symbol_loads_degraded(tmp_path):
    """End-to-end seam: a REAL .so carrying every pre-r17 symbol but not
    ``wire_assemble`` loads with strict=False, flags the degrade, and
    keeps the old symbols callable — no ctypes AttributeError
    mid-stream."""
    src = tmp_path / "stale.cpp"
    src.write_text(
        """
#include <cstdint>
extern "C" {
int32_t fasthash_batch(uint16_t*, int64_t*, int32_t, int32_t, int32_t,
                       int32_t*, float*, int32_t*, int32_t) { return 0; }
int32_t pad_units_batch(uint16_t*, int64_t*, int32_t, int32_t, int32_t,
                        int32_t, uint16_t*, int32_t*) { return 0; }
int32_t pad_units_batch_u8(uint16_t*, int64_t*, int32_t, int32_t, int32_t,
                           int32_t, uint8_t*, int32_t*) { return 0; }
void lexicon_score_batch(uint16_t*, int64_t*, int32_t, uint16_t*, int64_t*,
                         int32_t*, int32_t, uint16_t*, int64_t*, int32_t*,
                         int32_t, int32_t*, uint8_t*) {}
int64_t parse_tweet_block(const char*, int64_t, int64_t, int64_t, int64_t,
                          int64_t, int64_t*, uint16_t*, int64_t*, uint8_t*,
                          int64_t* c, int64_t* b) { *c = 0; *b = 0; return 0; }
int64_t parse_tweet_block_wire(const char*, int64_t, int64_t, int64_t,
                               int64_t, int64_t, int64_t*, uint8_t*,
                               uint16_t*, int64_t*, uint8_t*, int64_t* c,
                               int64_t* b, int64_t* n, int64_t* w) {
  *c = 0; *b = 0; *n = 1; *w = 0; return 0; }
int64_t digram_encode(const uint8_t*, int64_t, const uint8_t*, uint8_t*,
                      int64_t) { return 0; }
}
""",
        encoding="utf-8",
    )
    so = tmp_path / "stale.so"
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", "-o", str(so), str(src)],
        check=True, capture_output=True,
    )
    saved = native._assemble_missing
    try:
        with pytest.raises(AttributeError):
            native._load(str(so), strict=True)
        lib = native._load(str(so), strict=False)
        assert native._assemble_missing
        assert lib.digram_encode is not None  # old symbols still bound
    finally:
        native._assemble_missing = saved
        # every degrade flag, not just ours: the degraded _load also
        # flagged the r18 featurize symbol this stale lib lacks
        native.rebind_flags()


# ---------------------------------------------------------------------------
# mode plumbing


def test_configure_validates_and_env_default():
    with pytest.raises(ValueError):
        assemble.configure("maybe")
    prev = assemble.mode()
    assemble.configure("off")
    assert not assemble.available()
    assemble.configure(prev)


@needs_native
def test_assembled_counter_increments():
    from twtml_tpu.telemetry import metrics as _metrics

    reg = _metrics.get_registry()
    before = reg.counter("wire.assembled_native").snapshot()
    with assemble.forced("on"):
        pack_batch(hand_batch())
    assert reg.counter("wire.assembled_native").snapshot() == before + 1
