#!/bin/sh
# Distribution zip (equivalent of the reference's build.sh assembly zip:
# build.sh:6-16 bundles the spark jar + web jar; here one zip carries the
# python package, the native featurizer source, and the dashboard assets).
set -e
version="0.1.0"
cd "$(dirname "$0")/.."
rm -rf target && mkdir -p target
# minify dashboard assets (the reference's sbt-uglify step, web/build.sbt:25-39);
# the server serves file.min.js when present (web/server.py)
python tools/jsminify.py twtml_tpu/web/assets/js/api.js \
    twtml_tpu/web/assets/js/index.js twtml_tpu/web/assets/js/chart.js \
    twtml_tpu/web/assets/js/test.js
zip -qr "target/twtml-tpu-${version}.zip" \
    twtml_tpu native pyproject.toml README.md LICENSE bench.py \
    -x "*/__pycache__/*" -x "*.so"
echo "target/twtml-tpu-${version}.zip"
